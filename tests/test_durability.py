"""Durability plane (PR 16): per-node WAL + snapshot recovery under the
handoff PartitionStore seam.

Four layers under test, mirroring how the subsystem is built:

- the log itself (durability/wal.py): CRC'd length framing, torn-tail
  truncation at the first bad record, segment rotation, snapshot-marker
  retention, and old-frame tolerance (unknown record kinds skip, never
  crash a replayer);
- the durable store (durability/store.py): byte-for-byte parity with the
  in-memory reference store, log-over-snapshot replay with exact record
  counts, persisted NodeId/config-id identity, fsync policy accounting,
  and crash() stranding exactly what a real power loss would strand;
- the live cluster (tests/harness.py on virtual time): a crashed node
  rejoins with its OLD identity before the failure detector concludes,
  replays its log, passes fingerprint verification against its replica
  row, and loses zero acked writes -- including when its WAL tail was
  torn by the crash;
- the nemesis search: probe plans carrying the restart_node / torn_write
  rule families run the durability checker and stay clean with the bug
  flags off, deterministically per seed.
"""

import os

from rapid_tpu import InMemoryPartitionStore
from rapid_tpu.durability import (
    FSYNC_ALWAYS,
    FSYNC_BATCH,
    FSYNC_NEVER,
    DurablePartitionStore,
    tear_wal_tail,
)
from rapid_tpu.durability import wal as wal_mod
from rapid_tpu.search.runner import run_probe
from rapid_tpu.settings import DurabilitySettings, Settings
from rapid_tpu.types import NodeId

from harness import ClusterHarness


# ---------------------------------------------------------------------------
# the log: framing, torn tails, rotation, retention
# ---------------------------------------------------------------------------


class TestWalFraming:
    def test_frame_roundtrip_and_record_codecs(self):
        payloads = [
            wal_mod.put_record(7, b"content"),
            wal_mod.delete_record(7),
            wal_mod.snapshot_record(42),
            wal_mod.meta_record("node_id", b"\x01\x02"),
        ]
        blob = b"".join(wal_mod.frame(p) for p in payloads)
        decoded = [p for p, _end in wal_mod.iter_frames(blob)]
        assert decoded == payloads
        assert wal_mod.parse_record(payloads[0]) == (
            wal_mod.KIND_PUT, (7, b"content"))
        assert wal_mod.parse_record(payloads[1]) == (wal_mod.KIND_DELETE, (7,))
        assert wal_mod.parse_record(payloads[2]) == (
            wal_mod.KIND_SNAPSHOT, (42,))
        assert wal_mod.parse_record(payloads[3]) == (
            wal_mod.KIND_META, ("node_id", b"\x01\x02"))

    def test_unknown_kind_is_skipped_not_fatal(self):
        # a frame whose payload names a kind this replayer does not know is
        # a NEWER writer's record: the frame is intact, the content opaque
        assert wal_mod.parse_record(bytes([99]) + b"future bytes") is None
        assert wal_mod.parse_record(b"") is None

    def test_iter_frames_stops_at_short_and_corrupt_tails(self):
        good = wal_mod.frame(wal_mod.put_record(1, b"a"))
        torn_short = good + wal_mod.frame(wal_mod.put_record(2, b"bb"))[:-3]
        assert [p for p, _ in wal_mod.iter_frames(torn_short)] == [
            wal_mod.put_record(1, b"a")
        ]
        second = wal_mod.frame(wal_mod.put_record(2, b"bb"))
        corrupt = good + second[:-1] + bytes([second[-1] ^ 0xFF])
        assert [p for p, _ in wal_mod.iter_frames(corrupt)] == [
            wal_mod.put_record(1, b"a")
        ]

    def test_log_truncates_at_first_bad_record_on_reopen(self, tmp_path):
        directory = str(tmp_path)
        log = wal_mod.WriteAheadLog(directory, fsync_policy=FSYNC_NEVER)
        for i in range(5):
            log.append(wal_mod.put_record(i, b"rec-%d" % i))
        log.crash()
        assert tear_wal_tail(directory, drop_bytes=3) is not None
        reopened = wal_mod.WriteAheadLog(directory, fsync_policy=FSYNC_NEVER)
        records = [p for _seq, p in reopened.recovered_records()]
        assert records == [wal_mod.put_record(i, b"rec-%d" % i)
                           for i in range(4)]
        assert reopened.torn_truncations == 1
        # the truncation is physical: a third open sees a clean log
        reopened.close()
        clean = wal_mod.WriteAheadLog(directory, fsync_policy=FSYNC_NEVER)
        assert clean.torn_truncations == 0
        assert len(clean.recovered_records()) == 4
        clean.close()

    def test_corrupt_tail_truncates_via_crc_not_length(self, tmp_path):
        directory = str(tmp_path)
        log = wal_mod.WriteAheadLog(directory, fsync_policy=FSYNC_NEVER)
        for i in range(3):
            log.append(wal_mod.put_record(i, b"x" * 32))
        log.crash()
        assert tear_wal_tail(directory, corrupt=True) is not None
        reopened = wal_mod.WriteAheadLog(directory, fsync_policy=FSYNC_NEVER)
        assert reopened.torn_truncations == 1
        assert len(reopened.recovered_records()) == 2
        reopened.close()

    def test_rotation_and_snapshot_marker_retention(self, tmp_path):
        directory = str(tmp_path)
        # tiny segments: every ~2 records force a rotation
        log = wal_mod.WriteAheadLog(
            directory, segment_bytes=64, fsync_policy=FSYNC_BATCH
        )
        for i in range(10):
            log.append(wal_mod.put_record(i, b"y" * 16))
        assert len(log.segment_seqs()) > 1
        marker_seq = log.mark_snapshot(3)
        # retention: every segment below the marker is gone, and the marker
        # is the FIRST record of its (fresh) segment
        assert log.segment_seqs() == [marker_seq]
        log.append(wal_mod.put_record(99, b"after"))
        log.close()
        reopened = wal_mod.WriteAheadLog(directory, fsync_policy=FSYNC_BATCH)
        records = [p for _seq, p in reopened.recovered_records()]
        assert records[0] == wal_mod.snapshot_record(3)
        assert records[1] == wal_mod.put_record(99, b"after")
        reopened.close()

    def test_snapshot_file_without_witness_reads_as_absent(self, tmp_path):
        path = str(tmp_path / "snap.bin")
        wal_mod.write_snapshot(path, {1: b"a"}, {"k": b"v"})
        assert wal_mod.load_snapshot(path) == ({1: b"a"}, {"k": b"v"})
        # drop the terminal completeness witness: the file must read as
        # ABSENT (an interrupted snapshot), never as an empty/partial store
        with open(path, "r+b") as fh:
            fh.truncate(os.path.getsize(path) - 4)
        assert wal_mod.load_snapshot(path) is None


# ---------------------------------------------------------------------------
# the durable store: parity, replay counts, identity, crash semantics
# ---------------------------------------------------------------------------


def _seeded_workload(store, seed, ops=60, partitions=8):
    import random

    rnd = random.Random(seed)
    for i in range(ops):
        p = rnd.randrange(partitions)
        if rnd.random() < 0.85 or store.get(p) is None:
            store.put(p, b"w-%d-%d" % (seed, i))
        else:
            store.delete(p)


class TestDurableStore:
    def test_parity_with_in_memory_reference_store(self, tmp_path):
        durable = DurablePartitionStore(str(tmp_path), fsync_policy=FSYNC_NEVER)
        memory = InMemoryPartitionStore()
        _seeded_workload(durable, seed=11)
        _seeded_workload(memory, seed=11)
        assert durable.partitions() == memory.partitions()
        assert durable.sizes() == memory.sizes()
        for p in memory.partitions():
            assert durable.get(p) == memory.get(p)
            assert durable.fingerprint(p) == memory.fingerprint(p)
        durable.close()

    def test_recovery_replays_log_over_snapshot_with_exact_counts(
        self, tmp_path
    ):
        directory = str(tmp_path)
        store = DurablePartitionStore(
            directory, fsync_policy=FSYNC_NEVER, snapshot_every_records=0
        )
        store.set_identity(NodeId(123, 456))
        store.set_config_id(-77)
        for i in range(10):
            store.put(i, b"pre-%d" % i)
        store.checkpoint()
        for i in range(4):
            store.put(10 + i, b"post-%d" % i)
        expected = {p: store.get(p) for p in store.partitions()}
        store.crash()  # power loss: the tail lives only in the log
        reopened = DurablePartitionStore(
            directory, fsync_policy=FSYNC_NEVER, snapshot_every_records=0
        )
        stats = reopened.durability_stats()
        # the 10 pre-checkpoint puts came from the snapshot; only the 4
        # post-marker records replayed
        assert stats["replayed_records"] == 4
        assert stats["snapshot_version"] == 1
        assert {p: reopened.get(p) for p in reopened.partitions()} == expected
        # identity + config id survive the process (META records)
        assert reopened.node_id == NodeId(123, 456)
        assert reopened.config_id == -77
        assert stats["recovery_ms"] >= 0
        reopened.close()

    def test_auto_checkpoint_every_n_records(self, tmp_path):
        store = DurablePartitionStore(
            str(tmp_path), fsync_policy=FSYNC_NEVER, snapshot_every_records=8
        )
        for i in range(17):
            store.put(i % 4, b"v-%d" % i)
        stats = store.durability_stats()
        assert stats["snapshot_version"] == 2  # 17 records, cadence 8
        store.crash()
        reopened = DurablePartitionStore(
            str(tmp_path), fsync_policy=FSYNC_NEVER, snapshot_every_records=8
        )
        # only the single record past the second checkpoint replays
        assert reopened.durability_stats()["replayed_records"] == 1
        reopened.close()

    def test_fsync_policy_accounting_and_stall_hook_seam(self, tmp_path):
        stalls = []
        store = DurablePartitionStore(
            str(tmp_path / "always"), fsync_policy=FSYNC_ALWAYS,
            snapshot_every_records=0, fsync_hook=lambda: stalls.append(1),
        )
        for i in range(5):
            store.put(i, b"z")
        assert store.durability_stats()["fsyncs"] == 5  # one per append
        assert len(stalls) == 5  # disk_stall's injection point saw each
        store.close()

        lazy = DurablePartitionStore(
            str(tmp_path / "never"), fsync_policy=FSYNC_NEVER,
            snapshot_every_records=0,
        )
        for i in range(5):
            lazy.put(i, b"z")
        lazy.sync()
        assert lazy.durability_stats()["fsyncs"] == 0  # page cache only
        lazy.close()

    def test_crash_strands_all_further_mutation(self, tmp_path):
        store = DurablePartitionStore(
            str(tmp_path), fsync_policy=FSYNC_NEVER, snapshot_every_records=0
        )
        store.put(1, b"kept")
        store.crash()
        # a harness's graceful-shutdown path must not quietly rescue state
        # the crash should have stranded
        store.put(2, b"lost")
        store.delete(1)
        store.checkpoint()
        store.sync()
        reopened = DurablePartitionStore(
            str(tmp_path), fsync_policy=FSYNC_NEVER, snapshot_every_records=0
        )
        assert reopened.partitions() == (1,)
        assert reopened.get(1) == b"kept"
        reopened.close()

    def test_torn_write_recovery_is_deterministic_per_seed(self, tmp_path):
        """The ISSUE's pin: identical seeded workloads, identically torn,
        recover to identical states -- truncated at the first bad record,
        with exactly the final record lost."""
        digests = []
        for attempt in ("a", "b"):
            directory = str(tmp_path / attempt)
            store = DurablePartitionStore(
                directory, fsync_policy=FSYNC_NEVER, snapshot_every_records=0
            )
            _seeded_workload(store, seed=23)
            appended = store.durability_stats()["appends"]
            store.crash()
            assert tear_wal_tail(directory, corrupt=True) is not None
            recovered = DurablePartitionStore(
                directory, fsync_policy=FSYNC_NEVER, snapshot_every_records=0
            )
            stats = recovered.durability_stats()
            assert stats["torn_truncations"] == 1
            assert stats["replayed_records"] == appended - 1
            digests.append(recovered.digest())
            recovered.close()
        assert digests[0] == digests[1]


# ---------------------------------------------------------------------------
# the live cluster: crash, identity-preserving rejoin, catch-up
# ---------------------------------------------------------------------------


def _durable_harness(seed, tmp_path, n):
    settings = Settings(
        durability=DurabilitySettings(enabled=True, fsync_policy=FSYNC_NEVER)
    )
    h = ClusterHarness(seed=seed, settings=settings)
    placement = {"partitions": 16, "replicas": 3, "seed": 7}
    dirs = {i: str(tmp_path / f"node{i}") for i in range(n)}
    h.start_seed(0, placement=placement, serving=True, durability=dirs[0])
    for i in range(1, n):
        h.join(i, placement=placement, serving=True, durability=dirs[i])
    h.wait_and_verify_agreement(n)
    return h, placement, dirs


def _drive(h, cluster, acked, count, tag):
    for j in range(count):
        key = b"%s-%02d" % (tag, j)
        value = b"v-%s-%d" % (tag, j)
        promise = cluster.serving_put(key, value)
        ok = h.scheduler.run_until(promise.done, timeout_ms=60_000)
        if ok and promise.peek().status == 0:
            acked[key] = value


def _read_back(h, cluster, acked):
    lost = []
    for key in sorted(acked):
        promise = cluster.serving_get(key)
        h.scheduler.run_until(promise.done, timeout_ms=60_000)
        ack = promise.peek()
        if ack.status != 0 or ack.version == 0:
            lost.append(key)
    return lost


class TestClusterRecovery:
    def test_crashed_node_rejoins_with_old_identity_and_replays(
        self, tmp_path
    ):
        """The tentpole's acceptance path end to end: crash a serving node
        abruptly (WAL torn mid-flight, no clean stop), bring it back with
        the same durability directory BEFORE the failure detector
        concludes, and require: the persisted NodeId drives an
        identity-preserving rejoin, recovery replays log-over-snapshot,
        the recovered replica passes fingerprint verification against its
        row, and every acked write reads back."""
        n = 3
        h, placement, dirs = _durable_harness(19, tmp_path, n)
        try:
            victim = h.instances[h.addr(2)]
            identity = victim.get_partition_store().node_id
            assert identity is not None
            acked = {}
            _drive(h, h.instances[h.addr(0)], acked, 20, b"pre")
            assert len(acked) == 20
            h.scheduler.run_for(2_000)  # quiesce replication

            victim.get_partition_store().crash()  # power loss, not clean stop
            h.fail_nodes([h.addr(2)])
            h.blacklist.discard(h.addr(2))  # back before the FD concludes
            revived = h.join(2, seed_index=0, placement=placement,
                             serving=True, durability=dirs[2])
            h.wait_and_verify_agreement(n)

            store = revived.get_partition_store()
            assert store.node_id == identity  # SAME identity, not a new seat
            stats = store.durability_stats()
            assert stats["replayed_records"] > 0  # the log did the recovery
            # fingerprint verification against the replica row: with
            # replicas == n every node holds every partition, and the
            # recovered copy must agree byte-for-byte
            others = [
                h.instances[h.addr(i)].get_partition_store() for i in (0, 1)
            ]
            for p in store.partitions():
                for other in others:
                    if other.fingerprint(p) is not None:
                        assert other.fingerprint(p) == store.fingerprint(p), (
                            f"partition {p} diverged after recovery"
                        )
            _drive(h, h.instances[h.addr(1)], acked, 10, b"post")
            assert _read_back(h, h.instances[h.addr(0)], acked) == []
        finally:
            h.shutdown()

    def test_torn_wal_tail_truncates_and_cluster_converges(self, tmp_path):
        """A crash that also tears the victim's WAL tail (the torn_write
        family): recovery truncates at the first bad record, the node
        rejoins with its old identity, and the CLUSTER loses nothing --
        survivors still hold every acked write, and the next replicated
        write re-converges the damaged copy."""
        n = 3
        h, placement, dirs = _durable_harness(29, tmp_path, n)
        try:
            victim = h.instances[h.addr(1)]
            identity = victim.get_partition_store().node_id
            acked = {}
            _drive(h, h.instances[h.addr(0)], acked, 16, b"torn")
            h.scheduler.run_for(2_000)

            victim.get_partition_store().crash()
            assert tear_wal_tail(dirs[1], corrupt=True) is not None
            h.fail_nodes([h.addr(1)])
            h.blacklist.discard(h.addr(1))
            revived = h.join(1, seed_index=0, placement=placement,
                             serving=True, durability=dirs[1])
            h.wait_and_verify_agreement(n)

            store = revived.get_partition_store()
            assert store.node_id == identity
            assert store.durability_stats()["torn_truncations"] == 1
            # overwrite every key once: the quorum write re-replicates each
            # partition, converging the truncated copy with its row
            _drive(h, h.instances[h.addr(0)], acked, 16, b"torn")
            h.scheduler.run_for(2_000)
            others = [
                h.instances[h.addr(i)].get_partition_store() for i in (0, 2)
            ]
            for p in store.partitions():
                for other in others:
                    if other.fingerprint(p) is not None:
                        assert other.fingerprint(p) == store.fingerprint(p)
            # zero lost acked writes, torn tail and all
            assert _read_back(h, h.instances[h.addr(2)], acked) == []
        finally:
            h.shutdown()


# ---------------------------------------------------------------------------
# the nemesis search: restart/torn plans stay clean with the flags off
# ---------------------------------------------------------------------------

RESTART_PLAN = {"seed": 7, "rules": [
    {"type": "RestartNodeRule", "at": "egress", "windows": [[800, 2400]],
     "src": None, "dst": "node:7002", "msg_types": None},
    {"type": "TornWriteRule", "at": "egress", "windows": [[0, None]],
     "src": None, "dst": "node:7002", "msg_types": None,
     "drop_bytes": 3, "corrupt": False},
]}
RESTART_SPEC = {"harness": "engine", "n": 5, "partitions": 16, "replicas": 3,
                "horizon_ms": 4000, "ops": 40, "keys": 6,
                "plan": RESTART_PLAN}


class TestSearchDurability:
    def test_engine_restart_probe_clean_and_deterministic(self):
        """restart_node + torn_write on the engine fabric: the durability
        checker runs (restart rules arm it) and finds nothing with the
        bug flags off; the probe is bit-deterministic per seed."""
        first = run_probe(RESTART_SPEC)
        second = run_probe(RESTART_SPEC)
        assert first.violations == second.violations == ()
        assert first.coverage == second.coverage
        assert first.info == second.info
        # the restart actually happened: recovery landed in the journal
        assert ("kind", "durability_recovered") in first.coverage

    def test_sim_restart_probe_bills_replay_and_stays_clean(self):
        spec = {
            "harness": "sim", "n": 4, "capacity": 5, "horizon_ms": 20_000,
            "ops": 30, "keys": 8,
            "plan": {"seed": 5, "rules": [
                {"type": "RestartNodeRule", "at": "egress",
                 "windows": [[5000, 9000]], "src": None,
                 "dst": "10.0.0.2:5002", "msg_types": None},
            ]},
        }
        first = run_probe(spec)
        second = run_probe(spec)
        assert first.violations == second.violations == ()
        assert first.coverage == second.coverage
        # the durability mirror billed the victim's replay debt
        assert first.info["replayed_records"] >= 0
        assert first.info == second.info

    def test_budgeted_flag_off_hunt_with_restart_rules_runs_clean(self):
        """The satellite's acceptance hunt: GEN_RULES now samples
        restart_node / torn_write / disk_stall, and a budgeted hunt with
        every bug flag off must still find nothing."""
        from rapid_tpu.search.generator import GEN_RULES
        from rapid_tpu.search.hunt import Hunter

        assert {"RestartNodeRule", "TornWriteRule", "DiskStallRule"} <= set(
            GEN_RULES
        )
        report = Hunter(seed=3, budget=60, harness="engine",
                        shrink=False).run()
        assert report.probes == 60
        assert report.violations == []
