"""In-process N-node cluster harness on virtual time.

The equivalent of ClusterTest's buildCluster/waitAndVerifyAgreement machinery
(ClusterTest.java:711-778): full protocol, zero sockets, injectable failure
detectors and message drop/delay interceptors -- but deterministic and fast,
because timers run on the shared VirtualScheduler instead of wall clock.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Set

from rapid_tpu import ClusterBuilder, Cluster, Endpoint, Settings
from rapid_tpu.messaging.inprocess import (
    InProcessClient,
    InProcessNetwork,
    InProcessServer,
)
from rapid_tpu.monitoring.base import IEdgeFailureDetectorFactory
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.runtime.futures import Promise
from rapid_tpu.runtime.scheduler import VirtualScheduler

BASE_PORT = 1234

# Socket tests used blind randint port picks and collided when two batteries
# ran concurrently (VERDICT r4 weak #3); the probing reservation now lives
# in the package so examples/tools share it. Re-exported here because every
# socket test imports it from the harness.
from rapid_tpu.messaging.ports import free_port  # noqa (re-export)
from rapid_tpu.messaging.ports import free_port_base  # noqa (re-export)


class ClusterHarness:
    def __init__(self, seed: int = 0, use_static_fd: bool = True,
                 settings: Optional[Settings] = None) -> None:
        self.scheduler = VirtualScheduler()
        self.network = InProcessNetwork(self.scheduler)
        self.rng = random.Random(seed)
        self.settings = settings if settings is not None else Settings()
        self.blacklist: Set[Endpoint] = set()
        self.use_static_fd = use_static_fd
        self.instances: Dict[Endpoint, Cluster] = {}
        self.servers: Dict[Endpoint, InProcessServer] = {}
        # optional dissemination swap: factory(client, rng) -> IBroadcaster
        self.broadcaster_factory = None
        # optional armed fault plane (with_faults); wraps every node built
        self.nemesis = None

    def with_faults(self, plan) -> "ClusterHarness":
        """Arm a FaultPlan over this harness's virtual-time fabric: every
        node built afterwards gets its client/server pair wrapped in the
        nemesis decorators. Call ``self.nemesis.arm()`` again after bootstrap
        to restart the plan's windows from a healthy view."""
        from rapid_tpu.faults import Nemesis

        self.nemesis = Nemesis(plan, self.scheduler)
        return self

    def addr(self, i: int) -> Endpoint:
        return Endpoint.from_parts("127.0.0.1", BASE_PORT + i)

    def _builder(self, addr: Endpoint,
                 fd: Optional[IEdgeFailureDetectorFactory] = None,
                 metadata: Optional[Dict[str, bytes]] = None,
                 subscriptions=None,
                 placement: Optional[Dict[str, int]] = None,
                 handoff=None,
                 serving: bool = False,
                 durability: Optional[str] = None) -> ClusterBuilder:
        server = InProcessServer(addr, self.network)
        self.servers[addr] = server
        client = InProcessClient(addr, self.network, self.settings)
        scheduler = self.scheduler
        if self.nemesis is not None:
            client = self.nemesis.client(client, address=addr,
                                         settings=self.settings)
            server = self.nemesis.server(server, addr)
            # a ClockSkewRule'd node runs its ENTIRE timer stack (FD probe
            # intervals, batching windows, deadlines) on its drifted clock
            scheduler = self.nemesis.scheduler_for(addr)
        builder = (
            ClusterBuilder(addr)
            .set_messaging_client_and_server(client, server)
            .use_scheduler(scheduler)
            .use_settings(self.settings)
            .use_rng(random.Random(self.rng.getrandbits(64)))
        )
        if self.broadcaster_factory is not None:
            builder.set_broadcaster_factory(self.broadcaster_factory)
        if fd is not None:
            builder.set_edge_failure_detector_factory(fd)
        elif self.use_static_fd:
            builder.set_edge_failure_detector_factory(
                StaticFailureDetectorFactory(self.blacklist)
            )
        if metadata:
            builder.set_metadata(metadata)
        if placement:
            builder.use_placement(**placement)
        if handoff is not None:
            # a PartitionStore instance, or a factory called per node
            store = handoff() if callable(handoff) else handoff
            builder.use_handoff(store)
        if serving:
            builder.use_serving()
        if durability is not None:
            # per-node WAL directory; effective only when the harness's
            # Settings enable the durability plane (the kill switch)
            builder.use_durability(durability)
        for event, cb in subscriptions or []:
            builder.add_subscription(event, cb)
        return builder

    # -- cluster construction ------------------------------------------------

    def start_seed(self, i: int = 0, **kw) -> Cluster:
        cluster = self._builder(self.addr(i), **kw).start()
        self.instances[cluster.listen_address] = cluster
        return cluster

    def join_async(self, i: int, seed_index: int = 0, **kw) -> Promise:
        promise = self._builder(self.addr(i), **kw).join_async(self.addr(seed_index))

        def record(p: Promise) -> None:
            if p.exception() is None:
                cluster = p.peek()
                self.instances[cluster.listen_address] = cluster

        promise.add_callback(record)
        return promise

    def join(self, i: int, seed_index: int = 0, timeout_ms: int = 120_000, **kw) -> Cluster:
        promise = self.join_async(i, seed_index, **kw)
        ok = self.scheduler.run_until(promise.done, timeout_ms=timeout_ms)
        assert ok, f"join of node {i} timed out (virtual)"
        return promise.peek()

    def create_cluster(self, n: int, parallel: bool = True,
                       timeout_ms: int = 300_000) -> List[Cluster]:
        """Seed + (n-1) joiners, optionally all racing through the seed at once
        (ClusterTest.java:184-191)."""
        self.start_seed(0)
        if parallel:
            promises = [self.join_async(i) for i in range(1, n)]
            ok = self.scheduler.run_until(
                lambda: all(p.done() for p in promises), timeout_ms=timeout_ms
            )
            assert ok, "parallel joins timed out (virtual)"
            for p in promises:
                assert p.exception() is None, f"join failed: {p.exception()}"
        else:
            for i in range(1, n):
                self.join(i)
        return list(self.instances.values())

    # -- failure injection ---------------------------------------------------

    def fail_nodes(self, endpoints: List[Endpoint]) -> None:
        """Crash-stop: unregister the server and blacklist for static FDs
        (ClusterTest.failSomeNodes)."""
        for endpoint in endpoints:
            self.blacklist.add(endpoint)
            cluster = self.instances.pop(endpoint, None)
            if cluster is not None:
                cluster.shutdown()

    # -- convergence ---------------------------------------------------------

    def converged(self, expected_size: int) -> bool:
        instances = list(self.instances.values())
        if not instances:
            return False
        lists = []
        for instance in instances:
            members = instance.get_memberlist()
            if len(members) != expected_size:
                return False
            lists.append(members)
        first = lists[0]
        return all(lst == first for lst in lists)

    def wait_and_verify_agreement(self, expected_size: int,
                                  timeout_ms: int = 600_000,
                                  poll_ms: int = 500) -> None:
        """All live instances report identical member lists of expected size
        (ClusterTest.waitAndVerifyAgreement, ClusterTest.java:711-731)."""
        ok = self.scheduler.run_until(
            lambda: self.converged(expected_size), timeout_ms=timeout_ms,
            poll_ms=poll_ms,
        )
        if not ok:
            sizes = {
                str(ep): inst.get_membership_size()
                for ep, inst in self.instances.items()
            }
            raise AssertionError(
                f"no agreement on size {expected_size}; sizes: {sizes}"
            )
        configs = {
            inst.get_current_configuration_id() for inst in self.instances.values()
        }
        assert len(configs) == 1, f"diverging configuration ids: {configs}"

    def shutdown(self) -> None:
        for cluster in list(self.instances.values()):
            cluster.shutdown()
        self.instances.clear()
