"""Handoff plane: partition state transfer driven by placement diffs.

Four layers under test, mirroring how the subsystem is built:

- the pure planning core (handoff/plan.py): chunk schedules, content
  fingerprints, deterministic session ids, and the diff-driven transfer
  plans whose pairing must stay in lockstep with placement.diff_maps;
- the wire surface: HandoffRequest/HandoffChunk/HandoffAck through both
  the msgpack codec and the gRPC schema, plus the handoff columns of
  ClusterStatusResponse;
- the live engine (handoff/engine.py) on the in-process virtual-time
  harness: join-bootstrap pulls, removal-driven re-replication, fingerprint
  convergence across replicas, and nemesis batteries (chunk drop,
  duplication, reorder, source crash mid-session) that must still converge
  to verified ownership within bounded virtual time;
- the simulator mirror (sim/driver.py enable_handoff): deterministic
  store-to-store transfers under the fault plane, byte-identical metric
  trajectories across reruns of the same seed+plan.

The engine/device *plan* parity is pinned separately against the golden
vectors (test_golden_parity.py::test_handoff_plans_match_golden).
"""

import importlib.util
import os

import numpy as np
import pytest

from rapid_tpu import Endpoint, InMemoryPartitionStore
from rapid_tpu.faults import FaultPlan
from rapid_tpu.handoff import (
    chunk_spans,
    content_fingerprint,
    plan_transfers,
    session_key,
)
from rapid_tpu.handoff.device import session_keys_batch
from rapid_tpu.messaging import grpc_transport as gt
from rapid_tpu.messaging.codec import decode, encode
from rapid_tpu.messaging.wire_schema import MSG
from rapid_tpu.placement import PlacementConfig, build_map, diff_maps
from rapid_tpu.placement.engine import node_key64
from rapid_tpu.sim.driver import Simulator
from rapid_tpu.types import (
    ClusterStatusResponse,
    HandoffAck,
    HandoffChunk,
    HandoffRequest,
)

from harness import ClusterHarness


def members(n, base_port=9000):
    return [Endpoint.from_parts(f"10.0.{i // 200}.{i % 200}", base_port + i)
            for i in range(n)]


# ---------------------------------------------------------------------- #
# Planning core
# ---------------------------------------------------------------------- #

def test_chunk_spans_schedule():
    assert chunk_spans(0, 1024) == ()
    assert chunk_spans(1, 1024) == ((0, 1),)
    assert chunk_spans(1024, 1024) == ((0, 1024),)
    assert chunk_spans(2500, 1024) == ((0, 1024), (1024, 1024), (2048, 452))
    spans = chunk_spans(70977, 1 << 16)
    assert spans == ((0, 65536), (65536, 5441))
    with pytest.raises(ValueError):
        chunk_spans(10, 0)


def test_content_fingerprint_is_partition_seeded():
    data = b"identical bytes"
    assert content_fingerprint(3, data) == content_fingerprint(3, data)
    assert content_fingerprint(3, data) != content_fingerprint(4, data)
    assert content_fingerprint(0, b"") == content_fingerprint(0, b"")
    assert content_fingerprint(0, b"") != content_fingerprint(0, b"x")


def test_session_key_scalar_batch_parity():
    """The device plane's batched session ids are bit-identical to the
    scalar hash, including negative (signed-wrapped) versions."""
    rng = np.random.default_rng(5)
    versions = [7, -1234567890123, 0]
    partitions = rng.integers(0, 1 << 20, size=64).astype(np.int64)
    keys = rng.integers(-(1 << 62), 1 << 62, size=64).astype(np.int64)
    for version in versions:
        batch = session_keys_batch(version, partitions, keys, seed=11)
        for i in range(64):
            assert int(batch[i]) == session_key(
                version, int(partitions[i]), int(keys[i]), 11
            )


def test_inmemory_store_roundtrip():
    store = InMemoryPartitionStore()
    assert store.get(1) is None
    assert store.fingerprint(1) is None
    assert store.partitions() == ()
    store.put(1, b"abc")
    store.put(9, b"")
    assert store.get(1) == b"abc"
    assert store.partitions() == (1, 9)
    assert store.fingerprint(1) == content_fingerprint(1, b"abc")
    assert store.fingerprint(9) == content_fingerprint(9, b"")
    assert store.sizes() == {1: 3, 9: 0}
    ids, fps = store.digest()
    assert ids == (1, 9)
    assert fps == (store.fingerprint(1), store.fingerprint(9))
    store.put(1, b"abcd")  # overwrite refreshes the fingerprint
    assert store.fingerprint(1) == content_fingerprint(1, b"abcd")
    store.delete(1)
    assert store.get(1) is None
    assert store.partitions() == (9,)


def test_plan_transfers_pairing_and_failover_chains():
    """Plans cover exactly the diff's moved set, recipients are the arriving
    replicas, and failover chains contain only surviving members of the old
    row (a crashed donor is excluded)."""
    cfg = PlacementConfig(partitions=64, replicas=3, seed=2)
    eps = members(8)
    old_map = build_map(eps, {}, cfg, configuration_id=1)
    dead = eps[3]
    survivors = [ep for ep in eps if ep != dead]
    new_map = build_map(survivors, {}, cfg, configuration_id=2)
    diff = diff_maps(old_map, new_map)
    sizes = {p: (p * 977) % 5000 for p in range(cfg.partitions)}
    plans = plan_transfers(old_map, new_map, sizes, chunk_size=1024)

    assert {p.partition for p in plans} == set(diff.partitions_moved)
    assert len({p.session_id for p in plans}) == len(plans)
    seed = cfg.seed
    for plan in plans:
        old_row = old_map.assignments[plan.partition]
        new_row = new_map.assignments[plan.partition]
        assert plan.recipient in new_row and plan.recipient not in old_row
        assert dead not in plan.sources and dead != plan.recipient
        assert plan.sources, "removal always leaves a surviving replica"
        for src in plan.sources:
            assert src in old_row and src in new_map.members
        assert plan.size == sizes[plan.partition]
        assert plan.chunks == chunk_spans(plan.size, 1024)
        assert plan.session_id == session_key(
            new_map.version, plan.partition,
            node_key64(plan.recipient, seed), seed,
        )


def test_plan_transfers_rejects_config_mismatch():
    eps = members(4)
    a = build_map(eps, {}, PlacementConfig(8, 2, 1), configuration_id=1)
    b = build_map(eps, {}, PlacementConfig(8, 2, 2), configuration_id=1)
    with pytest.raises(ValueError):
        plan_transfers(a, b)


# ---------------------------------------------------------------------- #
# Wire surface
# ---------------------------------------------------------------------- #

def test_handoff_messages_survive_both_wires():
    """The three handoff messages round-trip bit-exactly through the
    msgpack codec (tags 19-21) and the gRPC oneofs."""
    ep = Endpoint.from_parts("10.1.2.3", 4567)
    req = HandoffRequest(sender=ep, session_id=-987654321, partition=31,
                         offset=65536, length=4096, map_version=-42)
    ack = HandoffAck(sender=ep, session_id=55, partition=0,
                     fingerprint=-1, map_version=7)
    chunk = HandoffChunk(sender=ep, session_id=55, partition=0, offset=128,
                         data=b"\x00\xff payload", total_size=9,
                         fingerprint=-12345,
                         status=HandoffChunk.STATUS_NOT_FOUND)
    for i, msg in enumerate((req, ack)):
        assert decode(encode(i, msg)) == (i, msg)
        wire = gt.to_wire_request(msg).SerializeToString(deterministic=True)
        assert gt.from_wire_request(
            MSG["RapidRequest"].FromString(wire)
        ) == msg
    assert decode(encode(9, chunk)) == (9, chunk)
    wire = gt.to_wire_response(chunk).SerializeToString(deterministic=True)
    assert gt.from_wire_response(MSG["RapidResponse"].FromString(wire)) == chunk
    empty = HandoffChunk(sender=ep, session_id=1, partition=2, offset=0)
    assert decode(encode(0, empty)) == (0, empty)
    wire = gt.to_wire_response(empty).SerializeToString(deterministic=True)
    assert gt.from_wire_response(MSG["RapidResponse"].FromString(wire)) == empty


def test_status_handoff_fields_survive_both_wires():
    """The handoff columns of ClusterStatusResponse (gRPC fields 16-20)
    round-trip through both wires; an old frame parses to the defaults."""
    r = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 1), configuration_id=9,
        membership_size=3, handoff_in_flight=2, handoff_completed=17,
        handoff_failed=1, handoff_partitions=(0, 3, 9),
        handoff_fingerprints=(-5, 0, 1 << 60),
    )
    assert decode(encode(4, r)) == (4, r)
    wire = gt.to_wire_response(r).SerializeToString(deterministic=True)
    assert gt.from_wire_response(MSG["RapidResponse"].FromString(wire)) == r
    old = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 1), configuration_id=1,
        membership_size=2,
    )
    wire = gt.to_wire_response(old).SerializeToString(deterministic=True)
    back = gt.from_wire_response(MSG["RapidResponse"].FromString(wire))
    assert back == old and back.handoff_partitions == ()


# ---------------------------------------------------------------------- #
# Live engine on the virtual-time harness
# ---------------------------------------------------------------------- #

PLACEMENT = {"partitions": 16, "replicas": 2, "seed": 5}


def _payload(p: int) -> bytes:
    """Deterministic per-partition content; partitions 1 and 7 exceed the
    engine's 64 KiB default chunk so the windowed multi-chunk pull path and
    its reassembly run against real data (partition 0 is empty content)."""
    size = (p * 977) % 3000 + (70_000 if p in (1, 7) else 0)
    return bytes((p * 7 + i) % 251 for i in range(size))


def _seeded_store() -> InMemoryPartitionStore:
    store = InMemoryPartitionStore()
    for p in range(PLACEMENT["partitions"]):
        store.put(p, _payload(p))
    return store


def _drain(h: ClusterHarness, timeout_ms: int = 600_000) -> None:
    ok = h.scheduler.run_until(
        lambda: all(inst.get_handoff_status()[0] == 0
                    for inst in h.instances.values()),
        timeout_ms=timeout_ms,
    )
    assert ok, "handoff sessions failed to drain in bounded virtual time"


def _verify_replicas(h: ClusterHarness) -> None:
    """Every replica the agreed map names holds byte-correct content."""
    maps = [inst.get_placement_map() for inst in h.instances.values()]
    assert len({m.version for m in maps}) == 1
    pmap = maps[0]
    for p, row in enumerate(pmap.assignments):
        expect = content_fingerprint(p, _payload(p))
        for ep in row:
            store = h.instances[ep].get_partition_store()
            data = store.get(p)
            assert data is not None, f"partition {p} missing on {ep}"
            assert content_fingerprint(p, data) == expect, (p, str(ep))


def test_cluster_handoff_join_and_removal_convergence():
    """The full ownership story: joiners bootstrap-pull the partitions the
    new map assigns them, a removal re-replicates from survivors, and after
    each churn every agreed replica's fingerprint matches the original
    bytes."""
    h = ClusterHarness(seed=3)
    try:
        h.start_seed(0, placement=PLACEMENT, handoff=_seeded_store())
        for i in (1, 2):
            h.join(i, placement=PLACEMENT, handoff=InMemoryPartitionStore)
        h.wait_and_verify_agreement(3)
        _drain(h)
        _verify_replicas(h)
        for i in (1, 2):
            inst = h.instances[h.addr(i)]
            in_flight, completed, failed = inst.get_handoff_status()
            assert (in_flight, failed) == (0, 0)
            assert completed > 0, f"joiner {i} bootstrapped nothing"
            assert inst.get_partition_store().partitions()

        h.fail_nodes([h.addr(2)])
        h.wait_and_verify_agreement(2)
        _drain(h)
        _verify_replicas(h)
        # the removal makes survivors recipients too (diff-driven path)
        total_completed = sum(
            inst.get_handoff_status()[1] for inst in h.instances.values()
        )
        assert total_completed > 0
        assert all(
            inst.get_handoff_status()[2] == 0 for inst in h.instances.values()
        )
    finally:
        h.shutdown()


def test_use_handoff_requires_placement():
    h = ClusterHarness(seed=1)
    try:
        with pytest.raises(ValueError):
            h.start_seed(0, handoff=InMemoryPartitionStore())
    finally:
        h.shutdown()


def _drop_plan():
    return FaultPlan(seed=13).drop(0.3, msg_types=(HandoffRequest,))


def _duplicate_plan():
    return FaultPlan(seed=13).duplicate(0.4, msg_types=(HandoffRequest,))


def _reorder_plan():
    return FaultPlan(seed=13).reorder(
        0.5, max_extra_ms=40, msg_types=(HandoffRequest,)
    )


def _combo_plan():
    return (FaultPlan(seed=13)
            .drop(0.2, msg_types=(HandoffRequest,))
            .duplicate(0.2, msg_types=(HandoffRequest,))
            .reorder(0.3, max_extra_ms=25, msg_types=(HandoffRequest,)))


@pytest.mark.parametrize("plan_fn", [
    _drop_plan, _duplicate_plan, _reorder_plan, _combo_plan,
], ids=["drop", "duplicate", "reorder", "drop+dup+reorder"])
def test_handoff_converges_under_nemesis(plan_fn):
    """Chunk-level drops, duplicates, and reorders on the pull RPCs --
    active from time zero, so bootstrap and removal transfers both suffer
    them -- still converge to verified ownership: retries ride the
    messaging-client deadlines, duplicates are idempotent by (session,
    offset), and failovers walk the surviving-replica chain."""
    h = ClusterHarness(seed=3).with_faults(plan_fn())
    h.nemesis.arm()
    try:
        h.start_seed(0, placement=PLACEMENT, handoff=_seeded_store())
        for i in (1, 2):
            h.join(i, placement=PLACEMENT, handoff=InMemoryPartitionStore)
        h.wait_and_verify_agreement(3)
        _drain(h)
        _verify_replicas(h)

        h.fail_nodes([h.addr(2)])
        h.wait_and_verify_agreement(2)
        _drain(h)
        _verify_replicas(h)
    finally:
        h.shutdown()


def test_handoff_source_crash_mid_session():
    """A source node dies while sessions are pulling from it (per-request
    delays keep the transfers in flight long enough to observe). The engine
    fails over to the next surviving replica and every remaining member
    converges to verified copies of all partitions."""
    placement = {"partitions": 16, "replicas": 3, "seed": 5}
    plan = FaultPlan(seed=4).delay(base_ms=400, msg_types=(HandoffRequest,))
    h = ClusterHarness(seed=6).with_faults(plan)
    h.nemesis.arm(epoch_ms=1 << 40)  # dormant while the cluster forms
    try:
        h.start_seed(0, placement=placement, handoff=_seeded_store())
        for i in (1, 2, 3):
            h.join(i, placement=placement, handoff=InMemoryPartitionStore)
        h.wait_and_verify_agreement(4)
        _drain(h)

        h.nemesis.arm()  # slow pulls from now on
        h.fail_nodes([h.addr(3)])
        # catch the rebalance with sessions still in flight...
        ok = h.scheduler.run_until(
            lambda: any(inst.get_handoff_status()[0] > 0
                        for inst in h.instances.values()),
            timeout_ms=300_000,
        )
        assert ok, "no handoff session observed in flight"
        # ...and crash a second node, taking live sources with it
        h.fail_nodes([h.addr(2)])
        h.wait_and_verify_agreement(2)
        _drain(h)
        _verify_replicas(h)
        assert all(
            inst.get_handoff_status()[2] == 0 for inst in h.instances.values()
        )
    finally:
        h.shutdown()


# ---------------------------------------------------------------------- #
# Simulator mirror
# ---------------------------------------------------------------------- #

_SIM_METRICS = (
    "handoff.sessions_started", "handoff.sessions_completed",
    "handoff.sessions_failed", "handoff.chunks_sent",
    "handoff.chunks_received", "handoff.chunks_duplicate",
    "handoff.bytes_moved", "handoff.retries", "handoff.failovers",
    "handoff.releases",
)


def _run_sim_churn(fault_plan=None) -> Simulator:
    sim = Simulator(3, capacity=5, seed=11).ready()
    sim.enable_placement(partitions=32, replicas=2, seed=7)
    sim.enable_handoff(chunk_size=1024, fault_plan=fault_plan)
    sim.request_joins(np.array([3]))
    assert sim.run_until_decision(max_rounds=20_000) is not None
    sim.crash(np.array([0]))
    assert sim.run_until_decision(max_rounds=20_000) is not None
    return sim


def _sim_metric_snapshot(sim: Simulator) -> dict:
    return {name: sim.metrics.get(name) for name in _SIM_METRICS}


def _verify_sim_stores(sim: Simulator) -> None:
    assign = sim.placement.assign
    sizes = sim._handoff_sizes
    stores = sim.handoff_stores
    for p in range(assign.shape[0]):
        expect = Simulator._handoff_payload(p, int(sizes[p]))
        for slot in assign[p]:
            if slot < 0:
                continue
            got = stores[int(slot)].get(p)
            assert got == expect, f"partition {p} wrong on slot {int(slot)}"


def test_sim_handoff_churn_completes_all_transfers():
    """Join + crash churn in the simulator: every diff's transfer plans run
    store-to-store, all sessions complete, and every owner the final map
    names holds byte-correct content."""
    sim = _run_sim_churn()
    snap = _sim_metric_snapshot(sim)
    assert snap["handoff.sessions_started"] > 0
    assert (
        snap["handoff.sessions_completed"] == snap["handoff.sessions_started"]
    )
    assert snap["handoff.sessions_failed"] == 0
    assert snap["handoff.bytes_moved"] > 0
    assert len(sim.handoff_transfers) == 2  # one plan list per view change
    assert all(sim.handoff_transfers)
    _verify_sim_stores(sim)


def test_sim_handoff_deterministic_under_nemesis():
    """The same seed + fault plan replays to an identical metric trajectory
    and virtual clock; the nemesis demonstrably bites (duplicates/retries
    observed) yet all sessions still complete and content converges."""
    def plan():
        return (FaultPlan(seed=5)
                .drop(0.3, msg_types=(HandoffRequest,))
                .duplicate(0.2, msg_types=(HandoffRequest,)))

    baseline = _run_sim_churn()
    a = _run_sim_churn(fault_plan=plan())
    b = _run_sim_churn(fault_plan=plan())
    snap_a, snap_b = _sim_metric_snapshot(a), _sim_metric_snapshot(b)
    assert snap_a == snap_b
    assert a.virtual_ms == b.virtual_ms
    assert snap_a["handoff.chunks_duplicate"] > 0
    assert snap_a["handoff.retries"] > 0
    assert snap_a["handoff.sessions_failed"] == 0
    assert (
        snap_a["handoff.sessions_completed"]
        == snap_a["handoff.sessions_started"]
    )
    # faults cost virtual time (retried chunk pulls bill per attempt) but
    # never change what moved
    assert a.virtual_ms >= baseline.virtual_ms
    assert (
        snap_a["handoff.sessions_started"]
        == _sim_metric_snapshot(baseline)["handoff.sessions_started"]
    )
    _verify_sim_stores(a)
    _verify_sim_stores(b)


def test_sim_enable_handoff_requires_placement():
    sim = Simulator(3, capacity=3, seed=1)
    with pytest.raises(RuntimeError):
        sim.enable_handoff()


# ---------------------------------------------------------------------- #
# statusz surfacing
# ---------------------------------------------------------------------- #

def _load_statusz():
    spec = importlib.util.spec_from_file_location(
        "statusz", os.path.join(os.path.dirname(__file__), "..", "tools",
                                "statusz.py")
    )
    statusz = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(statusz)
    return statusz


def test_statusz_surfaces_handoff_and_flags_divergence(monkeypatch, capsys):
    """tools/statusz.py renders the handoff session counts, exports the
    per-partition fingerprint map in JSON, and exits 2 when two replicas
    report different fingerprints for the same partition."""
    statusz = _load_statusz()
    a = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 1), configuration_id=5,
        membership_size=2, handoff_in_flight=1, handoff_completed=4,
        handoff_failed=0, handoff_partitions=(0, 1),
        handoff_fingerprints=(10, 20),
    )
    b = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 2), configuration_id=5,
        membership_size=2, handoff_completed=3,
        handoff_partitions=(1, 2), handoff_fingerprints=(99, 30),
    )
    text = statusz.render(a)
    assert "handoff: in-flight=1 completed=4 failed=0 stored=2" in text
    blob = statusz.to_json(a)
    assert blob["handoff_in_flight"] == 1
    assert blob["handoff_partitions"] == {"0": 10, "1": 20}
    bare = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 3), configuration_id=5,
        membership_size=2,
    )
    assert "handoff:" not in statusz.render(bare)

    replies = {"h1:1": a, "h2:2": b}
    monkeypatch.setattr(
        statusz, "fetch_status",
        lambda client, target, timeout: replies[
            f"{target.hostname.decode()}:{target.port}"
        ],
    )
    rc = statusz.main(["h1:1", "h2:2"])
    err = capsys.readouterr().err
    assert rc == 2
    assert "partition content fingerprints" in err
    assert "[1]" in err  # partition 1 is the one that diverges

    # agreeing fingerprints (disjoint or equal) do not trip the check
    replies["h2:2"] = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 2), configuration_id=5,
        membership_size=2, handoff_partitions=(1, 2),
        handoff_fingerprints=(20, 30),
    )
    assert statusz.main(["h1:1", "h2:2"]) == 0
