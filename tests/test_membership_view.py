"""MembershipView ring semantics, mirroring MembershipViewTest.java (499 LoC).

Scenarios: ring add/delete/duplicates, observer/subject cardinality, bootstrap
expected-observers, UUID-reuse rejection, configuration-ID uniqueness across
many adds, and order-independence of the final configuration.
"""

import random
import uuid

import pytest

from rapid_tpu.membership import (
    MembershipView,
    NodeAlreadyInRingError,
    NodeNotInRingError,
    UUIDAlreadySeenError,
)
from rapid_tpu.types import Endpoint, JoinStatusCode, NodeId

K = 10


def ep(i: int, host: str = "127.0.0.1") -> Endpoint:
    return Endpoint.from_parts(host, i)


def nid(rng: random.Random) -> NodeId:
    return NodeId.from_uuid(uuid.UUID(int=rng.getrandbits(128)))


def test_one_ring_add():
    rng = random.Random(0)
    view = MembershipView(K)
    view.ring_add(ep(1), nid(rng))
    assert view.membership_size == 1
    for k in range(K):
        assert len(view.get_ring(k)) == 1


def test_multiple_ring_additions():
    rng = random.Random(0)
    view = MembershipView(K)
    for i in range(10):
        view.ring_add(ep(i), nid(rng))
    assert view.membership_size == 10
    for k in range(K):
        assert len(view.get_ring(k)) == 10


def test_ring_readditions_throw():
    rng = random.Random(0)
    view = MembershipView(K)
    view.ring_add(ep(1), nid(rng))
    with pytest.raises(NodeAlreadyInRingError):
        view.ring_add(ep(1), nid(rng))


def test_delete_absent_node_throws():
    view = MembershipView(K)
    with pytest.raises(NodeNotInRingError):
        view.ring_delete(ep(1))


def test_ring_delete():
    rng = random.Random(0)
    view = MembershipView(K)
    for i in range(10):
        view.ring_add(ep(i), nid(rng))
    view.ring_delete(ep(5))
    assert view.membership_size == 9
    assert not view.is_host_present(ep(5))


def test_uuid_reuse_rejected():
    """MembershipViewTest.java:351-434 -- an identifier can be used once, ever."""
    rng = random.Random(0)
    view = MembershipView(K)
    identifier = nid(rng)
    view.ring_add(ep(1), identifier)
    with pytest.raises(UUIDAlreadySeenError):
        view.ring_add(ep(2), identifier)
    # even after deleting the original node
    view.ring_add(ep(3), nid(rng))
    view.ring_delete(ep(1))
    with pytest.raises(UUIDAlreadySeenError):
        view.ring_add(ep(4), identifier)
    assert view.is_safe_to_join(ep(4), identifier) == JoinStatusCode.UUID_ALREADY_IN_RING


def test_is_safe_to_join():
    rng = random.Random(0)
    view = MembershipView(K)
    identifier = nid(rng)
    view.ring_add(ep(1), identifier)
    assert view.is_safe_to_join(ep(1), nid(rng)) == JoinStatusCode.HOSTNAME_ALREADY_IN_RING
    assert view.is_safe_to_join(ep(2), identifier) == JoinStatusCode.UUID_ALREADY_IN_RING
    assert view.is_safe_to_join(ep(2), nid(rng)) == JoinStatusCode.SAFE_TO_JOIN


def test_observer_subject_cardinality():
    """At N >= K+1, every node has exactly K observers and K subjects
    (MembershipViewTest.java:268-293)."""
    rng = random.Random(1)
    view = MembershipView(K)
    n = K + 1
    for i in range(n):
        view.ring_add(ep(i), nid(rng))
    for i in range(n):
        assert len(view.get_observers_of(ep(i))) == K
        assert len(view.get_subjects_of(ep(i))) == K


def test_observers_are_ring_successors():
    """Observer on ring k is the successor on ring k; subject the predecessor."""
    rng = random.Random(2)
    view = MembershipView(K)
    n = 50
    for i in range(n):
        view.ring_add(ep(i), nid(rng))
    node = ep(7)
    observers = view.get_observers_of(node)
    subjects = view.get_subjects_of(node)
    for k in range(K):
        ring = view.get_ring(k)
        idx = ring.index(node)
        assert observers[k] == ring[(idx + 1) % n]
        assert subjects[k] == ring[(idx - 1) % n]
    # observer/subject duality: if s is subject of o on ring k, o observes s
    for k, s in enumerate(subjects):
        assert k in view.get_ring_numbers(node, s)


def test_expected_observers_of_absent_node():
    """Bootstrap gatekeepers for a joiner (MembershipViewTest.java:299-344)."""
    rng = random.Random(3)
    view = MembershipView(K)
    n = 20
    for i in range(n):
        view.ring_add(ep(i), nid(rng))
    joiner = ep(2000)
    expected = view.get_expected_observers_of(joiner)
    assert len(expected) == K
    # Reference quirk preserved: expected observers are the joiner's ring
    # *predecessors* (MembershipView.java:293-304 calls getPredecessorsOf),
    # which equal its post-join subjects -- while getObserversOf returns
    # successors. Insertion does not change which members precede the joiner.
    view.ring_add(joiner, nid(rng))
    assert view.get_subjects_of(joiner) == expected


def test_single_node_has_no_observers():
    rng = random.Random(4)
    view = MembershipView(K)
    view.ring_add(ep(1), nid(rng))
    assert view.get_observers_of(ep(1)) == []
    assert view.get_subjects_of(ep(1)) == []


def test_configuration_id_changes_on_every_add():
    """MembershipViewTest.java:442-455 (1000 adds, all IDs unique)."""
    rng = random.Random(5)
    view = MembershipView(K)
    seen = set()
    for i in range(1000):
        view.ring_add(ep(i), nid(rng))
        cid = view.get_current_configuration_id()
        assert cid not in seen
        seen.add(cid)


def test_configuration_order_independence():
    """Two views fed the same nodes in different orders converge to the same
    configuration ID (MembershipViewTest.java:464-499)."""
    rng = random.Random(6)
    nodes = [(ep(i), nid(rng)) for i in range(50)]
    v1 = MembershipView(K)
    v2 = MembershipView(K)
    for node, identifier in nodes:
        v1.ring_add(node, identifier)
    shuffled = nodes[:]
    random.Random(7).shuffle(shuffled)
    for node, identifier in shuffled:
        v2.ring_add(node, identifier)
    assert v1.get_current_configuration_id() == v2.get_current_configuration_id()
    assert v1.get_ring(0) == v2.get_ring(0)


def test_bootstrap_from_configuration():
    """A view rebuilt from a Configuration snapshot is identical
    (MembershipView.java:74-90, used by joiners, Cluster.java:442-474)."""
    rng = random.Random(8)
    view = MembershipView(K)
    for i in range(30):
        view.ring_add(ep(i), nid(rng))
    config = view.get_configuration()
    rebuilt = MembershipView(K, node_ids=config.node_ids, endpoints=config.endpoints)
    assert rebuilt.get_current_configuration_id() == view.get_current_configuration_id()
    for k in range(K):
        assert rebuilt.get_ring(k) == view.get_ring(k)


def test_ring_order_is_seed_dependent():
    """The K rings are distinct pseudo-random permutations."""
    rng = random.Random(9)
    view = MembershipView(K)
    for i in range(100):
        view.ring_add(ep(i), nid(rng))
    distinct = {tuple(view.get_ring(k)) for k in range(K)}
    assert len(distinct) == K
