"""The concurrency correctness suite is itself under test.

Three layers, all tier-1:

1. repo gates: ``python tools/concur.py`` and ``python tools/check.py --all``
   must exit 0 on today's tree (the analyzers are a merge gate, so the tree
   must stay finding-free);
2. rule fixtures: every rule fires on its ``tests/fixtures/concur/bad_*.py``
   exemplar and stays silent on the matching ``good_*.py`` -- both
   directions pinned, so a rule can neither silently die nor start
   misfiring on the corrected idiom;
3. runtime lockdep: the make_lock seam fails fast on order cycles and
   non-reentrant re-entry, records through blanket exception handlers, and
   costs nothing when RAPID_LOCKDEP is off.

The fixtures are never imported (several would deadlock); the analyzers read
them as text, and lintlib excludes ``fixtures`` dirs from every default scan.
"""

import subprocess
import sys
import threading
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "concur"

sys.path.insert(0, str(REPO / "tools"))

import check  # noqa: E402
import concur  # noqa: E402
from lintlib import Finding, iter_py_files  # noqa: E402


def _concur_rules(path: Path) -> set:
    return {f.rule for f in concur.run([str(path)])}


def _hygiene_rules(path: Path) -> set:
    # the two concurrency-hygiene rules live in check.py; general code-health
    # rules (unused-import etc.) are not what the fixtures pin
    return {
        f.rule
        for f in check.check_file(path)
        if f.rule in ("thread-daemon", "callback-under-lock")
    }


# ---------------------------------------------------------------------------
# 1. repo gates
# ---------------------------------------------------------------------------


def _run_tool(*argv):
    return subprocess.run(
        [sys.executable, *argv],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_concur_clean_on_repo():
    proc = _run_tool("tools/concur.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "concur: OK" in proc.stdout


def test_check_all_clean_on_repo():
    proc = _run_tool("tools/check.py", "--all")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "check+concur: OK" in proc.stdout


def test_check_rules_prints_full_catalog():
    proc = _run_tool("tools/check.py", "--rules")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for rule in check.RULE_DOCS:
        assert rule in proc.stdout


def test_default_scan_skips_fixture_corpus():
    """The deliberately-bad exemplars must never leak into a default scan."""
    scanned = iter_py_files([Path("tests")])
    assert scanned, "tests/ scan came back empty"
    assert not any("fixtures" in f.parts for f in scanned)


# ---------------------------------------------------------------------------
# 2. rule fixtures, both directions
# ---------------------------------------------------------------------------

CONCUR_FIXTURES = [
    ("bad_lock_order.py", "lock-order"),
    ("bad_unguarded_write.py", "unguarded-write"),
    ("bad_guard_not_held.py", "unguarded-write"),
    ("bad_blocking_under_lock.py", "blocking-under-lock"),
    ("bad_unbalanced_acquire.py", "unbalanced-acquire"),
    ("bad_jit_purity.py", "jit-purity"),
]

HYGIENE_FIXTURES = [
    ("bad_thread_daemon.py", "thread-daemon"),
    ("bad_callback_under_lock.py", "callback-under-lock"),
]

GOOD_CONCUR = [
    "good_lock_order.py",
    "good_unguarded_write.py",
    "good_blocking_under_lock.py",
    "good_unbalanced_acquire.py",
    "good_jit_purity.py",
]

GOOD_HYGIENE = [
    "good_thread_daemon.py",
    "good_callback_under_lock.py",
]


def test_fixture_corpus_is_complete():
    """Every fixture on disk is pinned by exactly one table above, and every
    table entry exists on disk -- a new fixture without a test (or a renamed
    fixture orphaning its pin) fails here."""
    on_disk = {f.name for f in FIXTURES.glob("*.py")}
    pinned = (
        {name for name, _ in CONCUR_FIXTURES}
        | {name for name, _ in HYGIENE_FIXTURES}
        | set(GOOD_CONCUR)
        | set(GOOD_HYGIENE)
    )
    assert pinned == on_disk


@pytest.mark.parametrize("name,rule", CONCUR_FIXTURES)
def test_concur_rule_fires_on_bad_fixture(name, rule):
    assert rule in _concur_rules(FIXTURES / name)


@pytest.mark.parametrize("name,rule", HYGIENE_FIXTURES)
def test_hygiene_rule_fires_on_bad_fixture(name, rule):
    assert rule in _hygiene_rules(FIXTURES / name)


@pytest.mark.parametrize("name", GOOD_CONCUR)
def test_concur_silent_on_good_fixture(name):
    assert _concur_rules(FIXTURES / name) == set()


@pytest.mark.parametrize("name", GOOD_HYGIENE)
def test_hygiene_silent_on_good_fixture(name):
    assert _hygiene_rules(FIXTURES / name) == set()


def test_noqa_suppresses_concur_finding(tmp_path):
    """`# noqa: RULE` is the one shared escape hatch; case-insensitive."""
    bad = (FIXTURES / "bad_blocking_under_lock.py").read_text()
    assert "time.sleep" in bad
    # suppress only the sleeping line, not the whole file; mixed case on
    # purpose -- rule matching is case-insensitive
    out = []
    for line in bad.splitlines(keepends=True):
        if "time.sleep" in line:
            line = line.rstrip("\n") + "  # noqa: Blocking-Under-Lock\n"
        out.append(line)
    target = tmp_path / "suppressed.py"
    target.write_text("".join(out))
    assert "blocking-under-lock" not in _concur_rules(target)


def test_every_emitted_rule_is_documented():
    """RULE_DOCS is the catalog of record: any rule a fixture can emit must
    have a one-line rationale there."""
    emitted = set()
    for name, rule in CONCUR_FIXTURES + HYGIENE_FIXTURES:
        emitted.add(rule)
    assert emitted <= set(check.RULE_DOCS)


def test_finding_renders_repo_relative():
    f = Finding(REPO / "rapid_tpu" / "cluster.py", 7, "lock-order", "boom")
    assert str(f) == "rapid_tpu/cluster.py:7: lock-order boom"


# ---------------------------------------------------------------------------
# 3. runtime lockdep
# ---------------------------------------------------------------------------

from rapid_tpu.runtime import lockdep  # noqa: E402


def test_lockdep_enabled_by_conftest():
    # the whole tier-1 suite runs instrumented (conftest sets RAPID_LOCKDEP=1
    # before any rapid_tpu import)
    assert lockdep.enabled()


def test_lockdep_detects_order_cycle():
    a = lockdep.make_lock("t_cycle.A")
    b = lockdep.make_lock("t_cycle.B")
    with a:
        with b:
            pass  # teaches the graph A -> B
    with b:
        with pytest.raises(lockdep.LockOrderViolation) as exc:
            a.acquire()
        assert "t_cycle.A" in str(exc.value) and "t_cycle.B" in str(exc.value)
    recorded = lockdep.consume_violations()
    assert len(recorded) == 1 and "closes a cycle" in recorded[0]


def test_lockdep_transitive_cycle_through_third_class():
    a = lockdep.make_lock("t_chain.A")
    b = lockdep.make_lock("t_chain.B")
    c = lockdep.make_lock("t_chain.C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with pytest.raises(lockdep.LockOrderViolation):
            a.acquire()  # A reaches C via B: C -> A closes the loop
    assert lockdep.consume_violations()


def test_lockdep_same_instance_reentry_fails_without_deadlocking():
    lock = lockdep.make_lock("t_reentry.L")
    with lock:
        # a plain threading.Lock would hang this thread forever here; the
        # wrapper must report instead of blocking
        with pytest.raises(lockdep.LockOrderViolation) as exc:
            lock.acquire()
    assert "re-entry" in str(exc.value)
    assert lockdep.consume_violations()


def test_lockdep_rlock_reentry_is_fine():
    lock = lockdep.make_rlock("t_rlock.L")
    with lock:
        with lock:
            pass
    assert lockdep.violations() == []


def test_lockdep_same_class_cross_instance_nesting_allowed():
    parent = lockdep.make_lock("t_sibling.Node._lock")
    child = lockdep.make_lock("t_sibling.Node._lock")
    with parent:
        with child:  # same class, different instances: no edge, no cycle
            pass
    assert lockdep.violations() == []


def test_lockdep_consistent_order_never_fires():
    a = lockdep.make_lock("t_consistent.A")
    b = lockdep.make_lock("t_consistent.B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert lockdep.violations() == []


def test_lockdep_violation_recorded_even_when_swallowed():
    """Protocol threads run under blanket handlers; the raise may vanish but
    the session gate must still see the violation."""
    a = lockdep.make_lock("t_swallow.A")
    b = lockdep.make_lock("t_swallow.B")
    with a:
        with b:
            pass

    def inverted():
        try:
            with b:
                with a:
                    pass
        except Exception:
            pass  # the blanket handler

    t = threading.Thread(target=inverted, daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    recorded = lockdep.consume_violations()
    assert len(recorded) == 1 and "t_swallow" in recorded[0]


def test_lockdep_locked_matches_threading_surface():
    lock = lockdep.make_lock("t_surface.L")
    assert not lock.locked()
    with lock:
        assert lock.locked()
    assert not lock.locked()


def test_lockdep_off_returns_plain_primitives(monkeypatch):
    monkeypatch.setenv("RAPID_LOCKDEP", "0")
    assert not lockdep.enabled()
    lock = lockdep.make_lock("t_off.L")
    rlock = lockdep.make_rlock("t_off.R")
    assert not isinstance(lock, lockdep._InstrumentedLock)
    assert not isinstance(rlock, lockdep._InstrumentedLock)
    with lock:
        pass
    with rlock:
        with rlock:
            pass


def test_lockdep_condition_never_instrumented():
    cond = lockdep.make_condition("t_cond.C")
    assert isinstance(cond, threading.Condition)
