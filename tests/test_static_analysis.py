"""The static-analysis + runtime-watchdog suites are themselves under test.

Five layers, all tier-1:

1. repo gates: ``python tools/concur.py``, ``python tools/devlint.py`` and
   ``python tools/check.py --all`` must exit 0 on today's tree (the
   analyzers are merge gates, so the tree must stay finding-free);
2. concurrency rule fixtures: every rule fires on its
   ``tests/fixtures/concur/bad_*.py`` exemplar and stays silent on the
   matching ``good_*.py`` -- both directions pinned, so a rule can neither
   silently die nor start misfiring on the corrected idiom;
3. runtime lockdep: the make_lock seam fails fast on order cycles and
   non-reentrant re-entry, records through blanket exception handlers, and
   costs nothing when RAPID_LOCKDEP is off;
4. device-plane rule fixtures: same both-directions contract for devlint's
   ``tests/fixtures/devlint`` corpus (recompile-hazard, host-sync,
   dtype-discipline, donation-hygiene);
5. runtime jitwatch: the make_jit seam records every compilation, enforces
   per-class budgets and steady-state timed windows (transfer guard armed),
   and records through blanket handlers like lockdep.

The fixtures are never imported (several would deadlock); the analyzers read
them as text, and lintlib excludes ``fixtures`` dirs from every default scan.
"""

import subprocess
import sys
import threading
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "concur"
DEV_FIXTURES = REPO / "tests" / "fixtures" / "devlint"

sys.path.insert(0, str(REPO / "tools"))

import check  # noqa: E402
import concur  # noqa: E402
import devlint  # noqa: E402
from lintlib import Finding, iter_py_files  # noqa: E402


def _concur_rules(path: Path) -> set:
    return {f.rule for f in concur.run([str(path)])}


def _devlint_rules(path: Path) -> set:
    return {f.rule for f in devlint.run([str(path)])}


def _hygiene_rules(path: Path) -> set:
    # the two concurrency-hygiene rules live in check.py; general code-health
    # rules (unused-import etc.) are not what the fixtures pin
    return {
        f.rule
        for f in check.check_file(path)
        if f.rule in ("thread-daemon", "callback-under-lock")
    }


# ---------------------------------------------------------------------------
# 1. repo gates
# ---------------------------------------------------------------------------


def _run_tool(*argv):
    return subprocess.run(
        [sys.executable, *argv],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_concur_clean_on_repo():
    proc = _run_tool("tools/concur.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "concur: OK" in proc.stdout


def test_check_all_clean_on_repo():
    proc = _run_tool("tools/check.py", "--all")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "check+concur+devlint: OK" in proc.stdout


def test_devlint_clean_on_repo():
    proc = _run_tool("tools/devlint.py")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "devlint: OK" in proc.stdout


def test_devlint_device_plane_paths_exist():
    """The default scan list must track the tree -- a renamed device module
    silently dropping out of the scan is itself a finding."""
    for rel in devlint.DEVICE_PLANE:
        assert (REPO / rel).exists(), f"devlint scans missing path {rel}"


def test_check_rules_prints_full_catalog():
    proc = _run_tool("tools/check.py", "--rules")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    for rule in check.RULE_DOCS:
        assert rule in proc.stdout


def test_settings_catalog_lint_clean_and_two_sided():
    """The settings-catalog lint passes on today's tree, and its contract
    holds at runtime too: SETTINGS_CATALOG keys are exactly the union of
    the cataloged settings groups' dataclass fields (check.SETTINGS_GROUPS,
    two-sided -- a knob without bounds or a stale catalog row both fail),
    with each group's default inside its bounds."""
    assert check.check_settings_catalog() == []
    import importlib
    from dataclasses import fields as dc_fields

    from rapid_tpu.settings import SETTINGS_CATALOG

    settings_mod = importlib.import_module("rapid_tpu.settings")
    knobs = set()
    for prefix, cls_name in check.SETTINGS_GROUPS.items():
        cls = getattr(settings_mod, cls_name)
        fields = {f"{prefix}.{f.name}" for f in dc_fields(cls)}
        assert fields <= set(SETTINGS_CATALOG), prefix
        knobs |= fields
        defaults = cls()
        for key in fields:
            entry = SETTINGS_CATALOG[key]
            value = getattr(defaults, key.split(".", 1)[1])
            if isinstance(value, bool):
                value = int(value)
            assert entry["min"] <= value <= entry["max"], key
            assert entry["doc"]
    assert set(SETTINGS_CATALOG) == knobs


def test_default_scan_skips_fixture_corpus():
    """The deliberately-bad exemplars must never leak into a default scan."""
    scanned = iter_py_files([Path("tests")])
    assert scanned, "tests/ scan came back empty"
    assert not any("fixtures" in f.parts for f in scanned)


# ---------------------------------------------------------------------------
# 2. rule fixtures, both directions
# ---------------------------------------------------------------------------

CONCUR_FIXTURES = [
    ("bad_lock_order.py", "lock-order"),
    ("bad_unguarded_write.py", "unguarded-write"),
    ("bad_guard_not_held.py", "unguarded-write"),
    ("bad_blocking_under_lock.py", "blocking-under-lock"),
    ("bad_unbalanced_acquire.py", "unbalanced-acquire"),
    ("bad_jit_purity.py", "jit-purity"),
]

HYGIENE_FIXTURES = [
    ("bad_thread_daemon.py", "thread-daemon"),
    ("bad_callback_under_lock.py", "callback-under-lock"),
]

GOOD_CONCUR = [
    "good_lock_order.py",
    "good_unguarded_write.py",
    "good_blocking_under_lock.py",
    "good_unbalanced_acquire.py",
    "good_jit_purity.py",
]

GOOD_HYGIENE = [
    "good_thread_daemon.py",
    "good_callback_under_lock.py",
]


def test_fixture_corpus_is_complete():
    """Every fixture on disk is pinned by exactly one table above, and every
    table entry exists on disk -- a new fixture without a test (or a renamed
    fixture orphaning its pin) fails here."""
    on_disk = {f.name for f in FIXTURES.glob("*.py")}
    pinned = (
        {name for name, _ in CONCUR_FIXTURES}
        | {name for name, _ in HYGIENE_FIXTURES}
        | set(GOOD_CONCUR)
        | set(GOOD_HYGIENE)
    )
    assert pinned == on_disk


@pytest.mark.parametrize("name,rule", CONCUR_FIXTURES)
def test_concur_rule_fires_on_bad_fixture(name, rule):
    assert rule in _concur_rules(FIXTURES / name)


@pytest.mark.parametrize("name,rule", HYGIENE_FIXTURES)
def test_hygiene_rule_fires_on_bad_fixture(name, rule):
    assert rule in _hygiene_rules(FIXTURES / name)


@pytest.mark.parametrize("name", GOOD_CONCUR)
def test_concur_silent_on_good_fixture(name):
    assert _concur_rules(FIXTURES / name) == set()


@pytest.mark.parametrize("name", GOOD_HYGIENE)
def test_hygiene_silent_on_good_fixture(name):
    assert _hygiene_rules(FIXTURES / name) == set()


def test_noqa_suppresses_concur_finding(tmp_path):
    """`# noqa: RULE` is the one shared escape hatch; case-insensitive."""
    bad = (FIXTURES / "bad_blocking_under_lock.py").read_text()
    assert "time.sleep" in bad
    # suppress only the sleeping line, not the whole file; mixed case on
    # purpose -- rule matching is case-insensitive
    out = []
    for line in bad.splitlines(keepends=True):
        if "time.sleep" in line:
            line = line.rstrip("\n") + "  # noqa: Blocking-Under-Lock\n"
        out.append(line)
    target = tmp_path / "suppressed.py"
    target.write_text("".join(out))
    assert "blocking-under-lock" not in _concur_rules(target)


def test_every_emitted_rule_is_documented():
    """RULE_DOCS is the catalog of record: any rule a fixture can emit must
    have a one-line rationale there."""
    emitted = set()
    for name, rule in CONCUR_FIXTURES + HYGIENE_FIXTURES:
        emitted.add(rule)
    assert emitted <= set(check.RULE_DOCS)


def test_finding_renders_repo_relative():
    f = Finding(REPO / "rapid_tpu" / "cluster.py", 7, "lock-order", "boom")
    assert str(f) == "rapid_tpu/cluster.py:7: lock-order boom"


# ---------------------------------------------------------------------------
# 3. runtime lockdep
# ---------------------------------------------------------------------------

from rapid_tpu.runtime import lockdep  # noqa: E402


def test_lockdep_enabled_by_conftest():
    # the whole tier-1 suite runs instrumented (conftest sets RAPID_LOCKDEP=1
    # before any rapid_tpu import)
    assert lockdep.enabled()


def test_lockdep_detects_order_cycle():
    a = lockdep.make_lock("t_cycle.A")
    b = lockdep.make_lock("t_cycle.B")
    with a:
        with b:
            pass  # teaches the graph A -> B
    with b:
        with pytest.raises(lockdep.LockOrderViolation) as exc:
            a.acquire()
        assert "t_cycle.A" in str(exc.value) and "t_cycle.B" in str(exc.value)
    recorded = lockdep.consume_violations()
    assert len(recorded) == 1 and "closes a cycle" in recorded[0]


def test_lockdep_transitive_cycle_through_third_class():
    a = lockdep.make_lock("t_chain.A")
    b = lockdep.make_lock("t_chain.B")
    c = lockdep.make_lock("t_chain.C")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with pytest.raises(lockdep.LockOrderViolation):
            a.acquire()  # A reaches C via B: C -> A closes the loop
    assert lockdep.consume_violations()


def test_lockdep_same_instance_reentry_fails_without_deadlocking():
    lock = lockdep.make_lock("t_reentry.L")
    with lock:
        # a plain threading.Lock would hang this thread forever here; the
        # wrapper must report instead of blocking
        with pytest.raises(lockdep.LockOrderViolation) as exc:
            lock.acquire()
    assert "re-entry" in str(exc.value)
    assert lockdep.consume_violations()


def test_lockdep_rlock_reentry_is_fine():
    lock = lockdep.make_rlock("t_rlock.L")
    with lock:
        with lock:
            pass
    assert lockdep.violations() == []


def test_lockdep_same_class_cross_instance_nesting_allowed():
    parent = lockdep.make_lock("t_sibling.Node._lock")
    child = lockdep.make_lock("t_sibling.Node._lock")
    with parent:
        with child:  # same class, different instances: no edge, no cycle
            pass
    assert lockdep.violations() == []


def test_lockdep_consistent_order_never_fires():
    a = lockdep.make_lock("t_consistent.A")
    b = lockdep.make_lock("t_consistent.B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert lockdep.violations() == []


def test_lockdep_violation_recorded_even_when_swallowed():
    """Protocol threads run under blanket handlers; the raise may vanish but
    the session gate must still see the violation."""
    a = lockdep.make_lock("t_swallow.A")
    b = lockdep.make_lock("t_swallow.B")
    with a:
        with b:
            pass

    def inverted():
        try:
            with b:
                with a:
                    pass
        except Exception:
            pass  # the blanket handler

    t = threading.Thread(target=inverted, daemon=True)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()
    recorded = lockdep.consume_violations()
    assert len(recorded) == 1 and "t_swallow" in recorded[0]


def test_lockdep_locked_matches_threading_surface():
    lock = lockdep.make_lock("t_surface.L")
    assert not lock.locked()
    with lock:
        assert lock.locked()
    assert not lock.locked()


def test_lockdep_off_returns_plain_primitives(monkeypatch):
    monkeypatch.setenv("RAPID_LOCKDEP", "0")
    assert not lockdep.enabled()
    lock = lockdep.make_lock("t_off.L")
    rlock = lockdep.make_rlock("t_off.R")
    assert not isinstance(lock, lockdep._InstrumentedLock)
    assert not isinstance(rlock, lockdep._InstrumentedLock)
    with lock:
        pass
    with rlock:
        with rlock:
            pass


def test_lockdep_condition_never_instrumented():
    cond = lockdep.make_condition("t_cond.C")
    assert isinstance(cond, threading.Condition)


# ---------------------------------------------------------------------------
# 4. devlint rule fixtures, both directions
# ---------------------------------------------------------------------------

DEVLINT_FIXTURES = [
    ("bad_recompile.py", "recompile-hazard"),
    ("bad_host_sync.py", "host-sync"),
    ("bad_dtype.py", "dtype-discipline"),
    ("bad_donation.py", "donation-hygiene"),
]

GOOD_DEVLINT = [
    "good_recompile.py",
    "good_host_sync.py",
    "good_dtype.py",
    "good_donation.py",
]


def test_devlint_fixture_corpus_is_complete():
    on_disk = {f.name for f in DEV_FIXTURES.glob("*.py")}
    pinned = {name for name, _ in DEVLINT_FIXTURES} | set(GOOD_DEVLINT)
    assert pinned == on_disk


@pytest.mark.parametrize("name,rule", DEVLINT_FIXTURES)
def test_devlint_rule_fires_on_bad_fixture(name, rule):
    # exactly its rule: the corpus is built so no exemplar cross-fires,
    # which keeps each bad_* a clean regression pin for one rule
    assert _devlint_rules(DEV_FIXTURES / name) == {rule}


@pytest.mark.parametrize("name", GOOD_DEVLINT)
def test_devlint_silent_on_good_fixture(name):
    assert _devlint_rules(DEV_FIXTURES / name) == set()


def test_devlint_tag_suppresses_finding(tmp_path):
    """`# devlint: <tag>` on (or up to TAG_WINDOW lines before) the finding
    line waives exactly the mapped rule -- the annotation system the real
    device plane uses for its deliberate sync points."""
    bad = (DEV_FIXTURES / "bad_donation.py").read_text()
    assert "state = advance(state, inputs)" in bad
    out = bad.replace(
        "state = advance(state, inputs)",
        "state = advance(state, inputs)  # devlint: no-donate",
    )
    target = tmp_path / "waived.py"
    target.write_text(out)
    assert _devlint_rules(target) == set()


def test_devlint_tag_window_is_backward_looking(tmp_path):
    """A tag placed AFTER the finding line must NOT suppress: annotations
    belong on or above the code they waive."""
    bad = (DEV_FIXTURES / "bad_donation.py").read_text()
    out = bad.replace(
        "state = advance(state, inputs)",
        "state = advance(state, inputs)\n        # devlint: no-donate",
    )
    target = tmp_path / "late_tag.py"
    target.write_text(out)
    assert "donation-hygiene" in _devlint_rules(target)


def test_devlint_honors_noqa(tmp_path):
    """lintlib's `# noqa: RULE` escape hatch works for devlint rules too."""
    bad = (DEV_FIXTURES / "bad_dtype.py").read_text()
    out = []
    for line in bad.splitlines(keepends=True):
        if "jnp." in line or "fd_" in line:
            line = line.rstrip("\n") + "  # noqa: dtype-discipline\n"
        out.append(line)
    target = tmp_path / "suppressed.py"
    target.write_text("".join(out))
    assert "dtype-discipline" not in _devlint_rules(target)


def test_devlint_rules_are_documented():
    """Every devlint rule the fixture corpus pins has a RULE_DOCS entry, so
    `tools/check.py --rules` stays the catalog of record."""
    emitted = {rule for _, rule in DEVLINT_FIXTURES}
    assert emitted <= set(check.RULE_DOCS)


# ---------------------------------------------------------------------------
# 5. runtime jitwatch
# ---------------------------------------------------------------------------

import jax.numpy as jnp  # noqa: E402

from rapid_tpu.runtime import jitwatch  # noqa: E402


def test_jitwatch_enabled_by_conftest():
    # the whole tier-1 suite runs instrumented (conftest sets
    # RAPID_JITWATCH=1 before any rapid_tpu import)
    assert jitwatch.enabled()


def test_jitwatch_records_compiles_and_signatures():
    f = jitwatch.make_jit("t_jw.sigs", lambda x: x + 1)
    before = jitwatch.compile_count("t_jw.sigs")
    f(jnp.zeros((2,), jnp.int32))
    f(jnp.zeros((2,), jnp.int32))  # warm: same signature, no new compile
    f(jnp.zeros((3,), jnp.int32))  # fresh shape: one more compile
    assert jitwatch.compile_count("t_jw.sigs") - before == 2
    sigs = jitwatch.signatures("t_jw.sigs")
    assert len(sigs) == 2 and sigs[0] != sigs[1]
    # the signature classes calls by abstract leaf shape/dtype
    assert "int32" in repr(sigs[0]) and "(2,)" in repr(sigs[0])


def test_jitwatch_static_args_class_by_value():
    f = jitwatch.make_jit("t_jw.static", lambda x, n: x * n,
                          static_argnums=(1,))
    f(jnp.zeros((2,), jnp.int32), 3)
    f(jnp.zeros((2,), jnp.int32), 4)  # same shapes, new static: recompile
    sigs = jitwatch.signatures("t_jw.static")
    assert len(sigs) == 2
    assert "('static', 3)" in repr(sigs[0])
    assert "('static', 4)" in repr(sigs[1])


def test_jitwatch_budget_breach_records_then_raises():
    f = jitwatch.make_jit("t_jw.budget", lambda x: x - 1, compile_budget=1)
    f(jnp.zeros((2,), jnp.int32))  # 1 <= budget
    with pytest.raises(jitwatch.JitwatchViolation) as exc:
        f(jnp.zeros((3,), jnp.int32))  # 2 > budget
    assert "over its budget" in str(exc.value)
    recorded = jitwatch.consume_violations()
    assert any("t_jw.budget" in v for v in recorded)


def test_jitwatch_steady_state_recompile_is_violation():
    f = jitwatch.make_jit("t_jw.steady", lambda x: x * 2)
    x4 = jnp.zeros((4,), jnp.float32)
    x5 = jnp.zeros((5,), jnp.float32)
    f(x4)  # warmup outside the window
    with jitwatch.timed_window("t_jw.window"):
        f(x4)  # warm signature inside the window: fine
        with pytest.raises(jitwatch.JitwatchViolation) as exc:
            f(x5)  # fresh shape inside the window: violation
    assert "steady-state recompile" in str(exc.value)
    assert "t_jw.window" in str(exc.value)
    recorded = jitwatch.consume_violations()
    assert any("t_jw.steady" in v for v in recorded)


def test_jitwatch_timed_window_arms_transfer_guard():
    """Implicit host->device transfers (python scalar materialization) fail
    at the offending line inside a window, and the propagating guard error
    is ALSO recorded so an outer blanket handler cannot hide it."""
    with pytest.raises(Exception) as exc:
        with jitwatch.timed_window("t_jw.guard"):
            jnp.int32(5)
    assert "transfer" in str(exc.value).lower()
    recorded = jitwatch.consume_violations()
    assert any("t_jw.guard" in v and "transfer-guard" in v for v in recorded)


def test_jitwatch_seams_allowed_inside_window():
    """The three audited seams work under an armed guard: fetch (explicit
    device->host), host_transfer (labeled re-allow), and warm watched
    dispatch -- and each seam use is counted."""
    f = jitwatch.make_jit("t_jw.seams", lambda x: x + 3)
    x = jnp.zeros((6,), jnp.int32)
    f(x)  # warm
    base_syncs = jitwatch.sync_counts()
    with jitwatch.timed_window("t_jw.seamwin"):
        out = f(x)
        host = jitwatch.fetch("t_jw.fetch", out)
        with jitwatch.host_transfer("t_jw.upload"):
            dev = jnp.int32(9)
    assert int(host[0]) == 3 and int(dev) == 9
    syncs = jitwatch.sync_counts()
    assert syncs.get("t_jw.fetch", 0) == base_syncs.get("t_jw.fetch", 0) + 1
    assert syncs.get("t_jw.upload", 0) == base_syncs.get("t_jw.upload", 0) + 1
    assert jitwatch.violations() == []


def test_jitwatch_drain_counts_barrier():
    x = jnp.ones((3,), jnp.float32)
    before = jitwatch.sync_counts().get("t_jw.drain", 0)
    jitwatch.drain("t_jw.drain", x)
    assert jitwatch.sync_counts().get("t_jw.drain", 0) == before + 1


def test_jitwatch_stats_snapshot_diffs():
    s0 = jitwatch.stats()
    f = jitwatch.make_jit("t_jw.stats", lambda x: x / 2)
    f(jnp.ones((2,), jnp.float32))
    s1 = jitwatch.stats()
    assert s1["compiles"] == s0["compiles"] + 1
    assert s1["compile_wall_s"] > s0["compile_wall_s"]


def test_jitwatch_off_returns_plain_jit(monkeypatch):
    monkeypatch.setenv("RAPID_JITWATCH", "0")
    assert not jitwatch.enabled()
    f = jitwatch.make_jit("t_jw.off", lambda x: x + 1)
    assert not isinstance(f, jitwatch._WatchedJit)
    assert int(f(jnp.int32(1))) == 2
    # seams are pass-through: no counting, no guard
    with jitwatch.timed_window("t_jw.offwin"):
        jnp.int32(5)  # would trip an armed guard
    jitwatch.fetch("t_jw.offfetch", jnp.int32(3))
    assert "t_jw.offfetch" not in jitwatch.sync_counts()


def test_jitwatch_wrapper_silenced_per_call(monkeypatch):
    """A wrapper created enabled can be silenced per call for A/B overhead
    runs -- no events recorded while the env var is 0."""
    f = jitwatch.make_jit("t_jw.silence", lambda x: x * 5)
    assert isinstance(f, jitwatch._WatchedJit)
    monkeypatch.setenv("RAPID_JITWATCH", "0")
    f(jnp.zeros((2,), jnp.int32))  # compiles, but unrecorded
    assert jitwatch.compile_count("t_jw.silence") == 0


def test_jitwatch_decorator_form():
    @jitwatch.make_jit("t_jw.deco")
    def bump(x):
        return x + 10

    assert isinstance(bump, jitwatch._WatchedJit)
    assert int(bump(jnp.int32(1))) == 11
    assert jitwatch.compile_count("t_jw.deco") == 1
