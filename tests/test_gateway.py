"""Socket-hosted TPU swarm: real agents over real TCP sockets against
TPU-hosted virtual peers (VERDICT r2 item 1 -- the north star, literally).

Each agent runs the untouched ClusterBuilder/Cluster stack on the real TCP
transport; destinations it cannot route locally (the swarm's synthetic
10.x.y.z virtual endpoints) ride a GatewayRoutedClient connection to the
SwarmGateway socket, which serializes them into the TPU simulator bridge.
Convergence and bit-identical configuration ids are asserted on both sides
of the wire.
"""

import time

from harness import free_port_base

import numpy as np
import pytest

from rapid_tpu import ClusterBuilder, Endpoint, Settings
from rapid_tpu.events import ClusterEvents
from rapid_tpu.messaging.gateway import (
    GatewaySwarmBroadcaster,
    GatewayRoutedClient,
    SwarmGateway,
    decode_routed,
    encode_routed,
)
from rapid_tpu.messaging.tcp import TcpClientServer
from rapid_tpu.types import PreJoinMessage, NodeId


def test_routed_frame_roundtrip():
    dst = Endpoint(b"10.1.2.3", 5042)
    msg = PreJoinMessage(
        sender=Endpoint(b"127.0.0.1", 9001), node_id=NodeId(-5, 77)
    )
    frame = encode_routed(123, dst, msg)
    request_no, dst_back, msg_back = decode_routed(frame)
    assert request_no == 123
    assert dst_back == dst
    assert msg_back == msg


class GatewayHarness:
    """A socket-hosted swarm plus real agents, all on loopback."""

    def __init__(self, n_virtual=32, seed=11, native_server=False,
                 capacity=None, fd_interval_ms=100, pump_interval_ms=50,
                 broadcaster_factory=None):
        # broadcaster_factory(routed_client, rng) -> IBroadcaster; default
        # is the wildcard-collapsing GatewaySwarmBroadcaster
        self.base = free_port_base(64)
        self.settings = Settings(
            failure_detector_interval_ms=fd_interval_ms,
            batching_window_ms=50,
            consensus_fallback_base_delay_ms=1000,
        )
        self.gateway = SwarmGateway(
            Endpoint.from_parts("127.0.0.1", self.base),
            n_virtual=n_virtual,
            capacity=capacity,
            seed=seed,
            settings=self.settings,
            pump_interval_ms=pump_interval_ms,
            native_server=native_server,
        )
        self.gateway.start()
        self.broadcaster_factory = broadcaster_factory
        self.agents = []

    def join_agent(self, i, timeout=60):
        addr = Endpoint.from_parts("127.0.0.1", self.base + i)
        transport = TcpClientServer(addr, self.settings)
        client = GatewayRoutedClient(
            addr, self.gateway.address, transport, self.settings
        )
        cluster = (
            ClusterBuilder(addr)
            .use_settings(self.settings)
            .set_messaging_client_and_server(client, transport)
            # swarm-bound broadcasts collapse to one wildcard frame, as the
            # agent CLI does in gateway mode
            .set_broadcaster_factory(
                self.broadcaster_factory
                if self.broadcaster_factory is not None
                else (
                    lambda c, rng, routed=client: GatewaySwarmBroadcaster(
                        routed
                    )
                )
            )
            .join(self.gateway.seed_endpoint(), timeout=timeout)
        )
        self.agents.append(cluster)
        return cluster

    def wait_converged(self, want, timeout=60, agents=None):
        agents = self.agents if agents is None else agents
        deadline = time.time() + timeout
        while time.time() < deadline:
            if (
                self.gateway.membership_size() == want
                and all(a.get_membership_size() == want for a in agents)
            ):
                return True
            time.sleep(0.1)
        # diagnosis on timeout: who lags, and at what size
        sizes = {}
        for a in agents:
            sizes.setdefault(a.get_membership_size(), []).append(
                a.listen_address.port
            )
        print(
            f"wait_converged({want}) timed out: gateway="
            f"{self.gateway.membership_size()}, agent sizes "
            f"{{size: [ports]}} = { {k: v for k, v in sorted(sizes.items())} }"
        )
        return False

    def shutdown(self):
        for a in self.agents:
            try:
                a.shutdown()
            except Exception:
                pass
        self.gateway.shutdown()


@pytest.mark.slow
def test_agents_join_socket_swarm_and_observe_cut():
    h = GatewayHarness(n_virtual=32, seed=11)
    try:
        a1 = h.join_agent(1)
        assert h.wait_converged(33, agents=[a1])
        assert a1.get_current_configuration_id() == h.gateway.configuration_id()

        a2 = h.join_agent(2)
        a3 = h.join_agent(3)
        assert h.wait_converged(35)
        # bit-identical configuration across the wire, all parties
        ids = {a.get_current_configuration_id() for a in h.agents}
        ids.add(h.gateway.configuration_id())
        assert len(ids) == 1
        lists = {tuple(a.get_memberlist()) for a in h.agents}
        assert len(lists) == 1
        assert len(lists.pop()) == 35

        # crash three virtual nodes; every real agent observes the exact cut
        events = []
        a1.register_subscription(
            ClusterEvents.VIEW_CHANGE, lambda cid, changes: events.append(changes)
        )
        victims = np.array([3, 11, 17])
        crashed_eps = {h.gateway.bridge.endpoint(int(v)) for v in victims}
        h.gateway.bridge.sim.crash(victims)
        assert h.wait_converged(32)
        ids = {a.get_current_configuration_id() for a in h.agents}
        ids.add(h.gateway.configuration_id())
        assert len(ids) == 1
        assert len(events) == 1
        assert {c.endpoint for c in events[0]} == crashed_eps
    finally:
        h.shutdown()


@pytest.mark.slow
def test_dead_agent_removed_from_socket_swarm():
    h = GatewayHarness(n_virtual=24, seed=12)
    try:
        a1 = h.join_agent(1)
        a2 = h.join_agent(2)
        assert h.wait_converged(26)
        a2.shutdown()  # abrupt death: socket closes, no leave
        h.agents.remove(a2)
        assert h.wait_converged(25, timeout=90)
        assert a1.get_current_configuration_id() == h.gateway.configuration_id()
        assert a2.listen_address not in a1.get_memberlist()
    finally:
        h.shutdown()


@pytest.mark.slow
def test_agent_leaves_socket_swarm_gracefully():
    h = GatewayHarness(n_virtual=24, seed=13)
    try:
        a1 = h.join_agent(1)
        a2 = h.join_agent(2)
        assert h.wait_converged(26)
        a2.leave_gracefully(timeout=60)
        h.agents.remove(a2)
        assert h.wait_converged(25, timeout=60)
        assert a1.get_current_configuration_id() == h.gateway.configuration_id()
    finally:
        h.shutdown()


@pytest.mark.slow
def test_gateway_checkpoint_restart_resume(tmp_path):
    """Checkpoint/resume across a gateway restart (SURVEY section 5.4 on the
    socket plane): the restored swarm keeps the configuration id and the
    real members' seats; live agents reconnect transparently, observe a new
    cut decided by the restored swarm, and a fresh agent can still join."""
    h = GatewayHarness(n_virtual=24, seed=14)
    snapshot = str(tmp_path / "swarm.npz")
    try:
        a1 = h.join_agent(1)
        a2 = h.join_agent(2)
        assert h.wait_converged(26)
        config_before = h.gateway.configuration_id()

        h.gateway.save(snapshot)
        h.gateway.shutdown()
        time.sleep(0.3)

        h.gateway = SwarmGateway(
            Endpoint.from_parts("127.0.0.1", h.base),
            restore_from=snapshot,
            settings=h.settings,
            pump_interval_ms=50,
        )
        h.gateway.start()
        assert h.gateway.configuration_id() == config_before
        assert h.gateway.membership_size() == 26
        # the restored bridge still knows which slots are real members
        assert set(h.gateway.bridge._real) == {
            a1.listen_address, a2.listen_address
        }

        # the restored swarm decides a new cut and the agents observe it
        victims = np.array([5, 17])
        h.gateway.bridge.sim.crash(victims)
        assert h.wait_converged(24, timeout=90)
        ids = {a.get_current_configuration_id() for a in h.agents}
        ids.add(h.gateway.configuration_id())
        assert len(ids) == 1

        # a brand-new agent joins the restored swarm
        a3 = h.join_agent(3)
        assert h.wait_converged(25)
        assert a3.get_current_configuration_id() == h.gateway.configuration_id()
    finally:
        h.shutdown()


@pytest.mark.slow
def test_rejoin_same_address_after_gateway_restore(tmp_path):
    """A member that was cut BEFORE the snapshot can rejoin on the same
    address AFTER the restore: stale endpoint->slot mappings must not
    resurrect (the restored bridge maps only seated endpoints, so the
    rejoiner is re-seated through the normal pre-join path and re-enters the
    real-member plane)."""
    h = GatewayHarness(n_virtual=24, seed=15)
    snapshot = str(tmp_path / "swarm.npz")
    try:
        a1 = h.join_agent(1)
        a2 = h.join_agent(2)
        assert h.wait_converged(26)
        dead_addr = a2.listen_address
        a2.shutdown()  # abrupt death; the swarm cuts it
        h.agents.remove(a2)
        assert h.wait_converged(25, timeout=90)

        h.gateway.save(snapshot)
        h.gateway.shutdown()
        h.gateway = SwarmGateway(
            Endpoint.from_parts("127.0.0.1", h.base),
            restore_from=snapshot,
            settings=h.settings,
            pump_interval_ms=50,
        )
        h.gateway.start()
        assert dead_addr not in h.gateway.bridge._slot_of  # no stale seat

        back = h.join_agent(dead_addr.port - h.base)  # same host:port
        assert h.wait_converged(26, timeout=90)
        assert back.listen_address == dead_addr
        assert dead_addr in h.gateway.bridge._real  # monitored + voting again
        ids = {a.get_current_configuration_id() for a in h.agents}
        ids.add(h.gateway.configuration_id())
        assert len(ids) == 1
    finally:
        h.shutdown()


@pytest.mark.slow
def test_socket_agents_against_mesh_sharded_swarm():
    """The full composition: external protocol-plane agents over real
    sockets against a swarm sharded over the 8-device mesh -- joins, votes,
    and cut observation all flow through the mesh round loop's early-exit
    dispatch, with configuration-id parity across the wire."""
    from rapid_tpu.shard.engine import make_mesh

    base = free_port_base(4)
    settings = Settings(
        failure_detector_interval_ms=100,
        batching_window_ms=50,
        consensus_fallback_base_delay_ms=1000,
    )
    gateway = SwarmGateway(
        Endpoint.from_parts("127.0.0.1", base),
        n_virtual=48,
        seed=16,
        settings=settings,
        pump_interval_ms=50,
        mesh=make_mesh(8),
    )
    gateway.start()
    agents = []
    try:
        for i in (1, 2):
            addr = Endpoint.from_parts("127.0.0.1", base + i)
            transport = TcpClientServer(addr, settings)
            client = GatewayRoutedClient(addr, gateway.address, transport, settings)
            agents.append(
                ClusterBuilder(addr)
                .use_settings(settings)
                .set_messaging_client_and_server(client, transport)
                .join(gateway.seed_endpoint(), timeout=90)
            )
        deadline = time.time() + 90
        while time.time() < deadline and not all(
            a.get_membership_size() == 50 for a in agents
        ):
            time.sleep(0.1)
        assert all(a.get_membership_size() == 50 for a in agents)
        ids = {a.get_current_configuration_id() for a in agents}
        ids.add(gateway.configuration_id())
        assert len(ids) == 1

        gateway.bridge.sim.crash(np.array([7, 23]))
        deadline = time.time() + 90
        while time.time() < deadline and not all(
            a.get_membership_size() == 48 for a in agents
        ):
            time.sleep(0.1)
        assert all(a.get_membership_size() == 48 for a in agents)
        ids = {a.get_current_configuration_id() for a in agents}
        ids.add(gateway.configuration_id())
        assert len(ids) == 1
    finally:
        for a in agents:
            a.shutdown()
        gateway.shutdown()


@pytest.mark.slow
@pytest.mark.skipif(
    not __import__("os").environ.get("RAPID_TPU_HEAVY"),
    reason="~5-minute flagship battery; set RAPID_TPU_HEAVY=1 to include "
    "(3/3 consecutive green on the 1-core build box, ROUND5.md item 1)",
)
def test_fifty_joiner_wave_and_churn_against_10k_swarm():
    """The reference's functional battery at real-socket scale (VERDICT r3
    item 7; ClusterTest.java:184-206 does a 100-node parallel join through
    one seed): 50 real agents race through the single seed endpoint into a
    10,000-virtual-node socket swarm -- concurrent joiners batch into shared
    view changes, stragglers whose phase-2 landed in a superseded
    configuration retry -- then a churn wave: five agents die abruptly (no
    leave), the simulated FDs cut them, and five fresh agents rejoin on the
    SAME addresses with fresh UUIDs. Config ids are asserted bit-identical
    across all parties after each phase."""
    import threading

    n_virtual = 10_000
    wave = 50
    # capacity must leave room for the whole wave (the default headroom of
    # 16 free slots would MEMBERSHIP_REJECT joiner #17, like a full ring);
    # FD/pump intervals are backed off from the small-harness defaults: 50
    # concurrent agent stacks plus the 10k simulator share this machine, and
    # a 100 ms probe cadence across 500 monitoring edges starves the joiners
    h = GatewayHarness(n_virtual=n_virtual, seed=17, capacity=n_virtual + 64,
                       fd_interval_ms=500, pump_interval_ms=150)
    # agents must find a warmed swarm: at 10k capacity the first jit compile
    # takes longer than a joiner's whole phase-1 retry budget
    h.gateway.warm()
    from rapid_tpu.cluster import JOIN_METRICS

    starved_before = JOIN_METRICS.get("join.phase1_no_response")
    exhausted_before = JOIN_METRICS.get("join.exhausted")
    errors = {}

    def join(i):
        try:
            h.join_agent(i, timeout=240)
        except Exception as exc:  # noqa: BLE001 -- collected and asserted
            errors[i] = exc

    try:
        # the wave arrives in staggered bursts of 10 concurrent joiners
        # (everything here -- 50 agent stacks, the gateway, and the 10k
        # XLA simulator -- shares this machine's cores; a single 50-wide
        # burst exhausts the joiners' 5 phase-1 retries behind the pump's
        # device dispatches before the seed can answer)
        for burst in range(0, wave, 10):
            threads = [
                threading.Thread(target=join, args=(i,), daemon=True)
                for i in range(burst + 1, burst + 11)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)
            assert not errors, f"joins failed: {errors}"
        assert len(h.agents) == wave
        # 120 s like the churn phase below: a straggler repaired by the
        # stale-traffic replay needs a replay round trip on a box where 50
        # member stacks share one core
        assert h.wait_converged(n_virtual + wave, timeout=120)
        ids = {a.get_current_configuration_id() for a in h.agents}
        ids.add(h.gateway.configuration_id())
        assert len(ids) == 1, f"diverging config ids after the wave: {ids}"

        # churn: an abrupt kill wave (sockets close, no LeaveMessage) ...
        victims, survivors = h.agents[:5], h.agents[5:]
        victim_addrs = [a.listen_address for a in victims]
        for a in victims:
            a.shutdown()
        h.agents = list(survivors)
        assert h.wait_converged(n_virtual + wave - 5, timeout=120)
        member_list = survivors[0].get_memberlist()
        assert all(addr not in member_list for addr in victim_addrs)

        # ... then a rejoin wave on the same addresses with fresh UUIDs
        rejoin_ports = [addr.port - h.base for addr in victim_addrs]
        threads = [
            threading.Thread(target=join, args=(p,), daemon=True)
            for p in rejoin_ports
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert not errors, f"rejoins failed: {errors}"
        assert h.wait_converged(n_virtual + wave, timeout=60)
        ids = {a.get_current_configuration_id() for a in h.agents}
        ids.add(h.gateway.configuration_id())
        assert len(ids) == 1, f"diverging config ids after churn: {ids}"
        # regression guard for the r4 starvation: not one joiner lost a
        # phase-1 attempt to a silent seed, and none burned all retries
        assert JOIN_METRICS.get("join.phase1_no_response") == starved_before
        assert JOIN_METRICS.get("join.exhausted") == exhausted_before
    finally:
        # protocol-thread accounting: on failure the log shows which task
        # class ate the thread
        for label, (count, total, worst) in sorted(
            h.gateway.task_stats().items(), key=lambda kv: -kv[1][1]
        ):
            print(f"protocol task {label}: n={count} total={total:.1f}s "
                  f"max={worst:.2f}s")
        h.shutdown()


@pytest.mark.slow
def test_agents_join_swarm_through_native_reactor():
    """The gateway's socket front door on the C++ epoll reactor
    (native_server=True): agents join, observe a virtual cut, and converge
    to the same config id -- everything above the accept/read loop
    unchanged."""
    from rapid_tpu.runtime.native_io import available

    if not available():
        pytest.skip("librapid_io.so unavailable (no toolchain)")
    h = GatewayHarness(n_virtual=24, seed=13, native_server=True)
    try:
        a1 = h.join_agent(1)
        a2 = h.join_agent(2)
        assert h.wait_converged(26)
        victims = [5, 9]
        h.gateway.bridge.sim.crash(np.array(victims))
        assert h.wait_converged(24)
        assert (
            a1.get_current_configuration_id()
            == a2.get_current_configuration_id()
            == h.gateway.configuration_id()
        )
    finally:
        h.shutdown()


@pytest.mark.slow
def test_agents_gossip_among_themselves_behind_gateway():
    """The socket-tier gossip composition (IBroadcaster.java:24-26 at the
    gateway): real agents disseminate alert batches and votes to EACH OTHER
    by epidemic relay (GatewayGossipBroadcaster) while the swarm still hears
    one wildcard copy. Joins, a virtual cut, and an abrupt agent death all
    converge with bit-identical configuration ids."""
    from rapid_tpu.messaging.gateway import GatewayGossipBroadcaster
    from rapid_tpu.messaging.gossip import GossipBroadcaster

    def factory(client, rng):
        return GatewayGossipBroadcaster(
            client,
            GossipBroadcaster(
                client, client.address, fanout=3, rng=rng, mode="pushpull"
            ),
        )

    h = GatewayHarness(n_virtual=32, seed=18, broadcaster_factory=factory)
    try:
        agents = [h.join_agent(i) for i in range(1, 7)]
        assert h.wait_converged(38)
        ids = {a.get_current_configuration_id() for a in h.agents}
        ids.add(h.gateway.configuration_id())
        assert len(ids) == 1

        # a virtual cut observed by every gossiping agent
        h.gateway.bridge.sim.crash(np.array([4, 21]))
        assert h.wait_converged(36, timeout=90)
        ids = {a.get_current_configuration_id() for a in h.agents}
        ids.add(h.gateway.configuration_id())
        assert len(ids) == 1

        # an abrupt agent death cut by the swarm FDs, gossip carrying the
        # survivors' alert/vote traffic
        victim = agents[-1]
        victim.shutdown()
        h.agents.remove(victim)
        assert h.wait_converged(35, timeout=120)
        assert victim.listen_address not in h.agents[0].get_memberlist()
        ids = {a.get_current_configuration_id() for a in h.agents}
        ids.add(h.gateway.configuration_id())
        assert len(ids) == 1
    finally:
        h.shutdown()
