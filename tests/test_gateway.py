"""Socket-hosted TPU swarm: real agents over real TCP sockets against
TPU-hosted virtual peers (VERDICT r2 item 1 -- the north star, literally).

Each agent runs the untouched ClusterBuilder/Cluster stack on the real TCP
transport; destinations it cannot route locally (the swarm's synthetic
10.x.y.z virtual endpoints) ride a GatewayRoutedClient connection to the
SwarmGateway socket, which serializes them into the TPU simulator bridge.
Convergence and bit-identical configuration ids are asserted on both sides
of the wire.
"""

import random
import time

import numpy as np
import pytest

from rapid_tpu import ClusterBuilder, Endpoint, Settings
from rapid_tpu.events import ClusterEvents
from rapid_tpu.messaging.gateway import (
    GatewaySwarmBroadcaster,
    GatewayRoutedClient,
    SwarmGateway,
    decode_routed,
    encode_routed,
)
from rapid_tpu.messaging.tcp import TcpClientServer
from rapid_tpu.types import PreJoinMessage, NodeId


def test_routed_frame_roundtrip():
    dst = Endpoint(b"10.1.2.3", 5042)
    msg = PreJoinMessage(
        sender=Endpoint(b"127.0.0.1", 9001), node_id=NodeId(-5, 77)
    )
    frame = encode_routed(123, dst, msg)
    request_no, dst_back, msg_back = decode_routed(frame)
    assert request_no == 123
    assert dst_back == dst
    assert msg_back == msg


class GatewayHarness:
    """A socket-hosted swarm plus real agents, all on loopback."""

    def __init__(self, n_virtual=32, seed=11, native_server=False):
        self.base = random.randint(20000, 29000)
        self.settings = Settings(
            failure_detector_interval_ms=100,
            batching_window_ms=50,
            consensus_fallback_base_delay_ms=1000,
        )
        self.gateway = SwarmGateway(
            Endpoint.from_parts("127.0.0.1", self.base),
            n_virtual=n_virtual,
            seed=seed,
            settings=self.settings,
            pump_interval_ms=50,
            native_server=native_server,
        )
        self.gateway.start()
        self.agents = []

    def join_agent(self, i, timeout=60):
        addr = Endpoint.from_parts("127.0.0.1", self.base + i)
        transport = TcpClientServer(addr, self.settings)
        client = GatewayRoutedClient(
            addr, self.gateway.address, transport, self.settings
        )
        cluster = (
            ClusterBuilder(addr)
            .use_settings(self.settings)
            .set_messaging_client_and_server(client, transport)
            # swarm-bound broadcasts collapse to one wildcard frame, as the
            # agent CLI does in gateway mode
            .set_broadcaster_factory(
                lambda c, rng, routed=client: GatewaySwarmBroadcaster(routed)
            )
            .join(self.gateway.seed_endpoint(), timeout=timeout)
        )
        self.agents.append(cluster)
        return cluster

    def wait_converged(self, want, timeout=60, agents=None):
        agents = self.agents if agents is None else agents
        deadline = time.time() + timeout
        while time.time() < deadline:
            if (
                self.gateway.membership_size() == want
                and all(a.get_membership_size() == want for a in agents)
            ):
                return True
            time.sleep(0.1)
        return False

    def shutdown(self):
        for a in self.agents:
            try:
                a.shutdown()
            except Exception:
                pass
        self.gateway.shutdown()


@pytest.mark.slow
def test_agents_join_socket_swarm_and_observe_cut():
    h = GatewayHarness(n_virtual=32, seed=11)
    try:
        a1 = h.join_agent(1)
        assert h.wait_converged(33, agents=[a1])
        assert a1.get_current_configuration_id() == h.gateway.configuration_id()

        a2 = h.join_agent(2)
        a3 = h.join_agent(3)
        assert h.wait_converged(35)
        # bit-identical configuration across the wire, all parties
        ids = {a.get_current_configuration_id() for a in h.agents}
        ids.add(h.gateway.configuration_id())
        assert len(ids) == 1
        lists = {tuple(a.get_memberlist()) for a in h.agents}
        assert len(lists) == 1
        assert len(lists.pop()) == 35

        # crash three virtual nodes; every real agent observes the exact cut
        events = []
        a1.register_subscription(
            ClusterEvents.VIEW_CHANGE, lambda cid, changes: events.append(changes)
        )
        victims = np.array([3, 11, 17])
        crashed_eps = {h.gateway.bridge.endpoint(int(v)) for v in victims}
        h.gateway.bridge.sim.crash(victims)
        assert h.wait_converged(32)
        ids = {a.get_current_configuration_id() for a in h.agents}
        ids.add(h.gateway.configuration_id())
        assert len(ids) == 1
        assert len(events) == 1
        assert {c.endpoint for c in events[0]} == crashed_eps
    finally:
        h.shutdown()


@pytest.mark.slow
def test_dead_agent_removed_from_socket_swarm():
    h = GatewayHarness(n_virtual=24, seed=12)
    try:
        a1 = h.join_agent(1)
        a2 = h.join_agent(2)
        assert h.wait_converged(26)
        a2.shutdown()  # abrupt death: socket closes, no leave
        h.agents.remove(a2)
        assert h.wait_converged(25, timeout=90)
        assert a1.get_current_configuration_id() == h.gateway.configuration_id()
        assert a2.listen_address not in a1.get_memberlist()
    finally:
        h.shutdown()


@pytest.mark.slow
def test_agent_leaves_socket_swarm_gracefully():
    h = GatewayHarness(n_virtual=24, seed=13)
    try:
        a1 = h.join_agent(1)
        a2 = h.join_agent(2)
        assert h.wait_converged(26)
        a2.leave_gracefully(timeout=60)
        h.agents.remove(a2)
        assert h.wait_converged(25, timeout=60)
        assert a1.get_current_configuration_id() == h.gateway.configuration_id()
    finally:
        h.shutdown()


@pytest.mark.slow
def test_gateway_checkpoint_restart_resume(tmp_path):
    """Checkpoint/resume across a gateway restart (SURVEY section 5.4 on the
    socket plane): the restored swarm keeps the configuration id and the
    real members' seats; live agents reconnect transparently, observe a new
    cut decided by the restored swarm, and a fresh agent can still join."""
    h = GatewayHarness(n_virtual=24, seed=14)
    snapshot = str(tmp_path / "swarm.npz")
    try:
        a1 = h.join_agent(1)
        a2 = h.join_agent(2)
        assert h.wait_converged(26)
        config_before = h.gateway.configuration_id()

        h.gateway.save(snapshot)
        h.gateway.shutdown()
        time.sleep(0.3)

        h.gateway = SwarmGateway(
            Endpoint.from_parts("127.0.0.1", h.base),
            restore_from=snapshot,
            settings=h.settings,
            pump_interval_ms=50,
        )
        h.gateway.start()
        assert h.gateway.configuration_id() == config_before
        assert h.gateway.membership_size() == 26
        # the restored bridge still knows which slots are real members
        assert set(h.gateway.bridge._real) == {
            a1.listen_address, a2.listen_address
        }

        # the restored swarm decides a new cut and the agents observe it
        victims = np.array([5, 17])
        h.gateway.bridge.sim.crash(victims)
        assert h.wait_converged(24, timeout=90)
        ids = {a.get_current_configuration_id() for a in h.agents}
        ids.add(h.gateway.configuration_id())
        assert len(ids) == 1

        # a brand-new agent joins the restored swarm
        a3 = h.join_agent(3)
        assert h.wait_converged(25)
        assert a3.get_current_configuration_id() == h.gateway.configuration_id()
    finally:
        h.shutdown()


@pytest.mark.slow
def test_rejoin_same_address_after_gateway_restore(tmp_path):
    """A member that was cut BEFORE the snapshot can rejoin on the same
    address AFTER the restore: stale endpoint->slot mappings must not
    resurrect (the restored bridge maps only seated endpoints, so the
    rejoiner is re-seated through the normal pre-join path and re-enters the
    real-member plane)."""
    h = GatewayHarness(n_virtual=24, seed=15)
    snapshot = str(tmp_path / "swarm.npz")
    try:
        a1 = h.join_agent(1)
        a2 = h.join_agent(2)
        assert h.wait_converged(26)
        dead_addr = a2.listen_address
        a2.shutdown()  # abrupt death; the swarm cuts it
        h.agents.remove(a2)
        assert h.wait_converged(25, timeout=90)

        h.gateway.save(snapshot)
        h.gateway.shutdown()
        h.gateway = SwarmGateway(
            Endpoint.from_parts("127.0.0.1", h.base),
            restore_from=snapshot,
            settings=h.settings,
            pump_interval_ms=50,
        )
        h.gateway.start()
        assert dead_addr not in h.gateway.bridge._slot_of  # no stale seat

        back = h.join_agent(dead_addr.port - h.base)  # same host:port
        assert h.wait_converged(26, timeout=90)
        assert back.listen_address == dead_addr
        assert dead_addr in h.gateway.bridge._real  # monitored + voting again
        ids = {a.get_current_configuration_id() for a in h.agents}
        ids.add(h.gateway.configuration_id())
        assert len(ids) == 1
    finally:
        h.shutdown()


@pytest.mark.slow
def test_socket_agents_against_mesh_sharded_swarm():
    """The full composition: external protocol-plane agents over real
    sockets against a swarm sharded over the 8-device mesh -- joins, votes,
    and cut observation all flow through the mesh round loop's early-exit
    dispatch, with configuration-id parity across the wire."""
    from rapid_tpu.shard.engine import make_mesh

    base = random.randint(20000, 29000)
    settings = Settings(
        failure_detector_interval_ms=100,
        batching_window_ms=50,
        consensus_fallback_base_delay_ms=1000,
    )
    gateway = SwarmGateway(
        Endpoint.from_parts("127.0.0.1", base),
        n_virtual=48,
        seed=16,
        settings=settings,
        pump_interval_ms=50,
        mesh=make_mesh(8),
    )
    gateway.start()
    agents = []
    try:
        for i in (1, 2):
            addr = Endpoint.from_parts("127.0.0.1", base + i)
            transport = TcpClientServer(addr, settings)
            client = GatewayRoutedClient(addr, gateway.address, transport, settings)
            agents.append(
                ClusterBuilder(addr)
                .use_settings(settings)
                .set_messaging_client_and_server(client, transport)
                .join(gateway.seed_endpoint(), timeout=90)
            )
        deadline = time.time() + 90
        while time.time() < deadline and not all(
            a.get_membership_size() == 50 for a in agents
        ):
            time.sleep(0.1)
        assert all(a.get_membership_size() == 50 for a in agents)
        ids = {a.get_current_configuration_id() for a in agents}
        ids.add(gateway.configuration_id())
        assert len(ids) == 1

        gateway.bridge.sim.crash(np.array([7, 23]))
        deadline = time.time() + 90
        while time.time() < deadline and not all(
            a.get_membership_size() == 48 for a in agents
        ):
            time.sleep(0.1)
        assert all(a.get_membership_size() == 48 for a in agents)
        ids = {a.get_current_configuration_id() for a in agents}
        ids.add(gateway.configuration_id())
        assert len(ids) == 1
    finally:
        for a in agents:
            a.shutdown()
        gateway.shutdown()


@pytest.mark.slow
def test_agents_join_swarm_through_native_reactor():
    """The gateway's socket front door on the C++ epoll reactor
    (native_server=True): agents join, observe a virtual cut, and converge
    to the same config id -- everything above the accept/read loop
    unchanged."""
    from rapid_tpu.runtime.native_io import available

    if not available():
        pytest.skip("librapid_io.so unavailable (no toolchain)")
    h = GatewayHarness(n_virtual=24, seed=13, native_server=True)
    try:
        a1 = h.join_agent(1)
        a2 = h.join_agent(2)
        assert h.wait_converged(26)
        victims = [5, 9]
        h.gateway.bridge.sim.crash(np.array(victims))
        assert h.wait_converged(24)
        assert (
            a1.get_current_configuration_id()
            == a2.get_current_configuration_id()
            == h.gateway.configuration_id()
        )
    finally:
        h.shutdown()
