"""Hierarchy plane: cells, deterministic leaders, composed global views.

The acceptance scenario is the one from the PR issue: a multi-cell
cluster (each cell an ordinary Rapid cluster) whose leader sets agree on
a composed global view; killing a member, killing a leader (failover is
a non-event), and killing a whole cell -- leader included -- must each
reconverge every survivor to one composed fingerprint, with the lost
cell evicted in O(1) parent rounds and zero collateral evictions.
Everything runs on virtual time, so the whole file is tier-1.
"""

from __future__ import annotations

from collections import defaultdict

import pytest

from rapid_tpu.hierarchy.cells import (
    cell_count,
    cell_members,
    cell_of,
    cell_of_endpoint,
    cell_sizes,
)
from rapid_tpu.hierarchy.parent import (
    CellState,
    GlobalView,
    cell_fingerprint,
    cell_leaders,
    compose_fingerprint,
    leader_key,
    parent_configuration_id,
)
from rapid_tpu.hierarchy.plane import HierarchyPlane
from rapid_tpu.hierarchy.routing import CellRouter
from rapid_tpu.messaging import codec
from rapid_tpu.messaging import grpc_transport as gt
from rapid_tpu.messaging.wire_schema import MSG
from rapid_tpu.settings import HierarchySettings, Settings
from rapid_tpu.sim.topology import LatencyTopology
from rapid_tpu.types import (
    CellDigestMessage,
    ClusterStatusResponse,
    Endpoint,
    GlobalViewMessage,
)

from harness import ClusterHarness


def _ep(i: int) -> Endpoint:
    return Endpoint(hostname=b"10.0.0.%d" % (i // 256), port=5000 + i)


def _hier_settings(**kw) -> Settings:
    kw.setdefault("enabled", True)
    return Settings(hierarchy=HierarchySettings(**kw))


# ---------------------------------------------------------------------------
# Cell assignment
# ---------------------------------------------------------------------------


class TestCells:
    def test_rendezvous_assignment_is_deterministic_and_in_range(self):
        eps = [_ep(i) for i in range(200)]
        cells = [cell_of_endpoint(ep, 8) for ep in eps]
        assert cells == [cell_of_endpoint(ep, 8) for ep in eps]
        assert all(0 <= c < 8 for c in cells)
        # rendezvous hashing spreads: no cell grabs everything
        assert len(set(cells)) == 8

    def test_single_cell_short_circuits(self):
        assert cell_of_endpoint(_ep(3), 1) == 0
        assert cell_of(_ep(3), 0) == 0  # no topology, no explicit count

    def test_rendezvous_is_minimally_disruptive(self):
        # growing 8 -> 9 cells only ever moves members INTO the new cell
        eps = [_ep(i) for i in range(300)]
        before = {ep: cell_of_endpoint(ep, 8) for ep in eps}
        after = {ep: cell_of_endpoint(ep, 9) for ep in eps}
        moved = [ep for ep in eps if before[ep] != after[ep]]
        assert all(after[ep] == 8 for ep in moved)

    def test_topology_zone_is_the_default_cell_boundary(self):
        topo = LatencyTopology(racks=8, zones=4)
        eps = [_ep(i) for i in range(16)]
        slots = {ep: i for i, ep in enumerate(eps)}
        for ep, slot in slots.items():
            assert (
                cell_of(ep, 0, topology=topo, slots=slots)
                == topo.zone_of(slot)
            )
        # an endpoint the slot map doesn't know falls back to rendezvous
        stranger = _ep(999)
        assert cell_of(stranger, 0, topology=topo, slots=slots) == (
            cell_of_endpoint(stranger, 4)
        )

    def test_cell_count_precedence(self):
        topo = LatencyTopology(racks=8, zones=4)
        assert cell_count(16, topo) == 16  # explicit wins
        assert cell_count(0, topo) == 4  # topology zones next
        assert cell_count(0, None) == 1  # flat fallback

    def test_cell_members_partitions_preserving_ring_order(self):
        eps = [_ep(i) for i in range(40)]
        groups = cell_members(eps, 4)
        flat = [ep for cell in sorted(groups) for ep in groups[cell]]
        assert sorted(map(str, flat)) == sorted(map(str, eps))
        for cell, members in groups.items():
            assert members == [ep for ep in eps if cell_of(ep, 4) == cell]
        assert cell_sizes(eps, 4) == tuple(
            (cell, len(groups[cell])) for cell in sorted(groups)
        )


# ---------------------------------------------------------------------------
# Leaders and the composed view
# ---------------------------------------------------------------------------


class TestParent:
    def test_leaders_are_a_pure_function_of_the_view(self):
        members = [_ep(i) for i in range(10)]
        a = cell_leaders(members, 3)
        b = cell_leaders(list(reversed(members)), 3)
        assert a == b
        assert len(a) == 3
        assert set(a) <= set(members)
        assert a == tuple(sorted(members, key=leader_key)[:3])

    def test_failover_promotes_the_next_in_leader_order(self):
        members = [_ep(i) for i in range(10)]
        order = sorted(members, key=leader_key)
        survivors = [ep for ep in members if ep != order[0]]
        assert cell_leaders(survivors, 1) == (order[1],)

    def test_parent_configuration_id_ignores_order_and_duplicates(self):
        leaders = [_ep(1), _ep(2), _ep(3)]
        a = parent_configuration_id(leaders)
        assert a == parent_configuration_id(list(reversed(leaders)))
        assert a == parent_configuration_id(leaders + [_ep(2)])
        assert a != parent_configuration_id(leaders[:2])

    def test_compose_fingerprint_covers_every_row_field(self):
        rows = [
            CellState(cell=0, epoch=11, size=5, leader="a:1"),
            CellState(cell=1, epoch=22, size=7, leader="b:2"),
        ]
        base = compose_fingerprint(rows)
        bumped = [rows[0], CellState(cell=1, epoch=23, size=7, leader="b:2")]
        assert base != compose_fingerprint(bumped)
        assert base == compose_fingerprint(list(reversed(rows)))

    def test_cell_fingerprint_is_membership_sensitive(self):
        members = [_ep(i) for i in range(5)]
        assert cell_fingerprint(members) == cell_fingerprint(members[::-1])
        assert cell_fingerprint(members) != cell_fingerprint(members[:-1])

    def test_global_view_install_and_evict(self):
        view = GlobalView()
        row = CellState(cell=2, epoch=5, size=3, leader="x:1")
        assert view.install(row) is True
        assert view.install(row) is False  # identical row is a no-op
        assert view.install(
            CellState(cell=2, epoch=6, size=3, leader="x:1")
        ) is True
        assert view.member_count() == 3
        assert view.evict_cell(2) is True
        assert view.evict_cell(2) is False
        assert view.rows() == ()


# ---------------------------------------------------------------------------
# Wire surface
# ---------------------------------------------------------------------------


DIGEST = CellDigestMessage(
    sender=_ep(1), cell=3, configuration_id=-77, membership_size=12,
    leader="10.0.0.0:5001", fingerprint=-12345, parent_round=9,
)
GLOBAL_VIEW = GlobalViewMessage(
    sender=_ep(1), parent_configuration_id=-9000, global_fingerprint=4242,
    cells=(0, 3), epochs=(-1, -77), sizes=(4, 12),
    leaders=("10.0.0.0:5000", "10.0.0.0:5001"), fingerprints=(1, 2),
    parent_round=9,
)


class TestWire:
    @pytest.mark.parametrize("msg", [DIGEST, GLOBAL_VIEW],
                             ids=["digest", "global_view"])
    def test_native_codec_roundtrip(self, msg):
        assert codec.decode(codec.encode(7, msg)) == (7, msg)

    @pytest.mark.parametrize("msg", [DIGEST, GLOBAL_VIEW],
                             ids=["digest", "global_view"])
    def test_grpc_roundtrip(self, msg):
        wire = gt.to_wire_request(msg)
        assert gt.from_wire_request(
            MSG["RapidRequest"].FromString(wire.SerializeToString())
        ) == msg

    def test_status_response_hierarchy_fields_roundtrip(self):
        resp = ClusterStatusResponse(
            sender=_ep(0), membership_size=11,
            configuration_id=-5, cell_id=2, cell_size=9,
            parent_configuration_id=-321, global_fingerprint=654,
            global_cells=(0, 2), global_epochs=(-5, -6),
            global_sizes=(4, 9), global_leaders=("a:1", "b:2"),
        )
        wire = gt.to_wire_response(resp)
        assert gt.from_wire_response(
            MSG["RapidResponse"].FromString(wire.SerializeToString())
        ) == resp

    def test_flat_status_response_skips_hierarchy_fields_on_the_wire(self):
        # proto3 zero-defaults: a flat-mode response must serialize to the
        # exact pre-hierarchy bytes (also golden-pinned in test_profiling)
        resp = ClusterStatusResponse(
            sender=_ep(0), membership_size=3, configuration_id=-5
        )
        wire = gt.to_wire_response(resp).SerializeToString(deterministic=True)
        hierarchy_fields = {46, 47, 48, 49, 50, 51, 52, 53}
        seen = {
            field.number
            for field, _ in MSG["RapidResponse"].FromString(
                wire
            ).clusterStatusResponse.ListFields()
        }
        assert not (seen & hierarchy_fields)


# ---------------------------------------------------------------------------
# Plane unit semantics (fake channel, no cluster)
# ---------------------------------------------------------------------------


class _FakeChannel:
    def __init__(self):
        self.leader_sends = []  # (recipients, msg)
        self.cell_sends = []  # (recipients, msg)

    def send_to_leaders(self, leaders, msg):
        self.leader_sends.append((tuple(leaders), msg))
        return len(tuple(leaders))

    def send_to_cell(self, members, msg):
        self.cell_sends.append((tuple(members), msg))
        return len(tuple(members))


def _plane_for(members, cells=4, **kw):
    """A plane for the member of ``members`` that leads its cell."""
    groups = cell_members(members, cells)
    cell, cellmates = next(iter(sorted(groups.items())))
    leader = cell_leaders(cellmates, 1)[0]
    chan = _FakeChannel()
    plane = HierarchyPlane(leader, channel=chan, cells=cells, **kw)
    plane.on_view_installed(cellmates, configuration_id=-100)
    return plane, chan, cellmates


class TestPlane:
    def test_view_install_refreshes_own_row(self):
        plane, _, cellmates = _plane_for([_ep(i) for i in range(24)])
        own = plane.global_view.cells[plane.my_cell]
        assert own.epoch == -100
        assert own.size == len(cellmates)
        assert own.leader == str(plane._my_addr)
        assert plane.is_leader

    def test_follower_does_not_advance_rounds(self):
        members = [_ep(i) for i in range(24)]
        groups = cell_members(members, 4)
        cell, cellmates = next(iter(sorted(groups.items())))
        follower = [
            ep for ep in cellmates
            if ep != cell_leaders(cellmates, 1)[0]
        ][0]
        plane = HierarchyPlane(follower, channel=_FakeChannel(), cells=4)
        plane.on_view_installed(cellmates, configuration_id=-100)
        assert not plane.is_leader
        assert plane.parent_round == 0

    def test_stale_digest_from_same_leader_is_gated(self):
        plane, _, _ = _plane_for([_ep(i) for i in range(24)])
        other = next(c for c in range(4) if c != plane.my_cell)
        fresh = CellDigestMessage(
            sender=_ep(400), cell=other, configuration_id=-1,
            membership_size=6, leader="l:1", fingerprint=111, parent_round=5,
        )
        plane.handle_digest(fresh)
        stale = CellDigestMessage(
            sender=_ep(400), cell=other, configuration_id=-2,
            membership_size=9, leader="l:1", fingerprint=222, parent_round=3,
        )
        plane.handle_digest(stale)
        assert plane.global_view.cells[other].fingerprint == 111
        # a changed leader resets the gate (deterministic failover)
        takeover = CellDigestMessage(
            sender=_ep(401), cell=other, configuration_id=-3,
            membership_size=5, leader="l2:1", fingerprint=333, parent_round=0,
        )
        plane.handle_digest(takeover)
        assert plane.global_view.cells[other].fingerprint == 333

    def test_own_cell_row_is_never_adopted_from_the_wire(self):
        plane, _, cellmates = _plane_for([_ep(i) for i in range(24)])
        poison = CellDigestMessage(
            sender=_ep(400), cell=plane.my_cell, configuration_id=-999,
            membership_size=1, leader="evil:1", fingerprint=666,
            parent_round=50,
        )
        plane.handle_digest(poison)
        assert plane.global_view.cells[plane.my_cell].size == len(cellmates)

    def test_follower_relays_digests_to_its_leader(self):
        members = [_ep(i) for i in range(24)]
        groups = cell_members(members, 4)
        cell, cellmates = next(iter(sorted(groups.items())))
        leader = cell_leaders(cellmates, 1)[0]
        follower = [ep for ep in cellmates if ep != leader][0]
        chan = _FakeChannel()
        plane = HierarchyPlane(follower, channel=chan, cells=4)
        plane.on_view_installed(cellmates, configuration_id=-100)
        other = next(c for c in range(4) if c != cell)
        msg = CellDigestMessage(
            sender=_ep(400), cell=other, configuration_id=-1,
            membership_size=6, leader="l:1", fingerprint=1, parent_round=1,
        )
        plane.handle_digest(msg)
        assert chan.leader_sends == [((leader,), msg)]

    def test_tick_evicts_idle_cells_and_fans_the_removal(self):
        plane, chan, _ = _plane_for(
            [_ep(i) for i in range(24)], eviction_rounds=3
        )
        other = next(c for c in range(4) if c != plane.my_cell)
        plane.handle_digest(CellDigestMessage(
            sender=_ep(400), cell=other, configuration_id=-1,
            membership_size=6, leader="l:1", fingerprint=1, parent_round=1,
        ))
        assert other in plane.global_view.cells
        chan.cell_sends.clear()
        for _ in range(3):
            plane.tick()
        assert other not in plane.global_view.cells
        # the eviction was fanned into the cell so followers adopt it
        fanned = chan.cell_sends[-1][1]
        assert isinstance(fanned, GlobalViewMessage)
        assert other not in fanned.cells

    def test_followers_adopt_evictions_via_absent_row_diff(self):
        members = [_ep(i) for i in range(24)]
        groups = cell_members(members, 4)
        cell, cellmates = next(iter(sorted(groups.items())))
        leader = cell_leaders(cellmates, 1)[0]
        follower = [ep for ep in cellmates if ep != leader][0]
        plane = HierarchyPlane(follower, channel=_FakeChannel(), cells=4)
        plane.on_view_installed(cellmates, configuration_id=-100)
        other = next(c for c in range(4) if c != cell)
        plane.handle_digest(CellDigestMessage(
            sender=_ep(400), cell=other, configuration_id=-1,
            membership_size=6, leader="l:1", fingerprint=1, parent_round=1,
        ))
        assert other in plane.global_view.cells
        plane.handle_global_view(GlobalViewMessage(
            sender=leader, parent_configuration_id=1, global_fingerprint=2,
            cells=(cell,), epochs=(-100,), sizes=(len(cellmates),),
            leaders=(str(leader),), fingerprints=(0,), parent_round=4,
        ))
        assert other not in plane.global_view.cells

    def test_status_fields_shape(self):
        plane, _, cellmates = _plane_for([_ep(i) for i in range(24)])
        fields = plane.status_fields()
        assert fields["cell_id"] == plane.my_cell
        assert fields["cell_size"] == len(cellmates)
        assert fields["global_cells"] == (plane.my_cell,)
        assert set(fields) == {
            "cell_id", "cell_size", "parent_configuration_id",
            "global_fingerprint", "global_cells", "global_epochs",
            "global_sizes", "global_leaders",
        }


# ---------------------------------------------------------------------------
# Cell router (broadcast confinement)
# ---------------------------------------------------------------------------


class _RecordingBroadcaster:
    def __init__(self):
        self.recipients = None

    def broadcast(self, msg):
        return []

    def set_membership(self, recipients):
        self.recipients = list(recipients)


class TestCellRouter:
    def test_set_membership_confines_to_own_cell(self):
        members = [_ep(i) for i in range(40)]
        inner = _RecordingBroadcaster()
        me = members[0]
        router = CellRouter(inner, me, 4)
        router.set_membership(members)
        mine = cell_of(me, 4)
        assert inner.recipients == [
            ep for ep in members if cell_of(ep, 4) == mine
        ]
        assert me in inner.recipients


# ---------------------------------------------------------------------------
# Engine integration: the acceptance scenario on virtual time
# ---------------------------------------------------------------------------


def _boot_cells(h: ClusterHarness, n: int, cells: int):
    """Bootstrap each cell as its own Rapid cluster; returns cell->indices."""
    by_cell = defaultdict(list)
    for i in range(n):
        by_cell[cell_of_endpoint(h.addr(i), cells)].append(i)
    for idxs in by_cell.values():
        h.start_seed(idxs[0])
        for i in idxs[1:]:
            h.join(i, seed_index=idxs[0])
    seed_eps = [h.addr(idxs[0]) for idxs in by_cell.values()]
    for inst in h.instances.values():
        inst.hierarchy.seed_parent(seed_eps)
    return dict(by_cell)


def _agreed(h: ClusterHarness, expected_cells) -> bool:
    fingerprints = set()
    for inst in h.instances.values():
        plane = inst.hierarchy
        if set(plane.global_view.cells) != set(expected_cells):
            return False
        fingerprints.add(plane.global_view.fingerprint())
    return len(fingerprints) == 1


class TestEngineIntegration:
    def test_composed_view_agreement_member_kill_and_whole_cell_loss(self):
        h = ClusterHarness(
            seed=7, settings=_hier_settings(cells=4, parent_flush_ms=0)
        )
        by_cell = _boot_cells(h, 24, 4)
        assert h.scheduler.run_until(
            lambda: _agreed(h, by_cell), timeout_ms=600_000
        ), "composed views never agreed after bootstrap"
        any_plane = next(iter(h.instances.values())).hierarchy
        assert any_plane.global_view.member_count() == 24

        # single-member kill inside the largest cell: local churn, global
        # agreement follows the cell's own digest
        big = max(by_cell, key=lambda c: len(by_cell[c]))
        h.fail_nodes([h.addr(by_cell[big][-1])])
        assert h.scheduler.run_until(
            lambda: _agreed(h, by_cell) and next(
                iter(h.instances.values())
            ).hierarchy.global_view.member_count() == 23,
            timeout_ms=1_200_000,
        ), "agreement lost after a single-member kill"

        # whole-cell loss, leader included: survivors evict it in O(1)
        # parent rounds with zero collateral evictions
        small = min(by_cell, key=lambda c: len(by_cell[c]))
        h.fail_nodes([h.addr(i) for i in by_cell[small]])
        remaining = set(by_cell) - {small}
        assert h.scheduler.run_until(
            lambda: _agreed(h, remaining), timeout_ms=2_400_000
        ), "whole-cell loss never evicted from the composed view"
        for c in remaining:
            alive = [i for i in by_cell[c] if h.addr(i) in h.instances]
            for i in alive:
                assert len(
                    h.instances[h.addr(i)].get_memberlist()
                ) == len(alive), "collateral eviction in a surviving cell"

    def test_leader_failover_is_a_non_event(self):
        h = ClusterHarness(
            seed=11, settings=_hier_settings(cells=3, parent_flush_ms=0)
        )
        by_cell = _boot_cells(h, 18, 3)
        assert h.scheduler.run_until(
            lambda: _agreed(h, by_cell), timeout_ms=600_000
        )
        # kill the rank-0 leader of the largest cell
        big = max(by_cell, key=lambda c: len(by_cell[c]))
        cellmates = [h.addr(i) for i in by_cell[big]]
        old_leader = cell_leaders(cellmates, 1)[0]
        survivors = [ep for ep in cellmates if ep != old_leader]
        new_leader = cell_leaders(survivors, 1)[0]
        h.fail_nodes([old_leader])

        def failed_over():
            if not _agreed(h, by_cell):
                return False
            for inst in h.instances.values():
                row = inst.hierarchy.global_view.cells[big]
                if row.leader != str(new_leader) or row.size != len(survivors):
                    return False
            return True

        assert h.scheduler.run_until(failed_over, timeout_ms=1_200_000), (
            "leader failover did not converge to the next deterministic "
            "leader"
        )
        # no other cell saw churn
        for c, idxs in by_cell.items():
            if c == big:
                continue
            for i in idxs:
                assert len(
                    h.instances[h.addr(i)].get_memberlist()
                ) == len(idxs)

    def test_kill_switch_off_has_no_plane(self):
        h = ClusterHarness(seed=3)
        h.start_seed(0)
        inst = h.instances[h.addr(0)]
        assert inst.hierarchy is None
        status = inst.get_cluster_status()
        assert status.cell_id == 0
        assert status.global_cells == ()


# ---------------------------------------------------------------------------
# statusz: hierarchy digest rendering + composed-fingerprint disagreement
# ---------------------------------------------------------------------------


def _load_statusz():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "statusz", os.path.join(os.path.dirname(__file__), "..", "tools",
                                "statusz.py")
    )
    statusz = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(statusz)
    return statusz


def test_statusz_flags_global_fingerprint_disagreement(monkeypatch, capsys):
    """tools/statusz.py renders the per-member hierarchy digest (cell id,
    cell size, parent configuration id), exports the composed view in
    JSON, and exits 2 when hierarchy-enabled members disagree on the
    composed global-view fingerprint."""
    statusz = _load_statusz()
    a = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 1), configuration_id=5,
        membership_size=4, cell_id=1, cell_size=2,
        parent_configuration_id=777, global_fingerprint=4242,
        global_cells=(0, 1), global_epochs=(10, 11),
        global_sizes=(2, 2), global_leaders=("h:1", "h:3"),
    )
    text = statusz.render(a)
    assert ("hierarchy: cell=1 cell-size=2 parent-config=777"
            " cells=2 members=4 fingerprint=4242") in text
    blob = statusz.to_json(a)
    assert blob["hierarchy"]["parent_configuration_id"] == 777
    assert blob["hierarchy"]["cells"]["1"] == {
        "epoch": 11, "size": 2, "leader": "h:3",
    }
    # flat members render no hierarchy line and export None
    bare = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 9), configuration_id=5,
        membership_size=4,
    )
    assert "hierarchy:" not in statusz.render(bare)
    assert statusz.to_json(bare)["hierarchy"] is None

    diverged = ClusterStatusResponse(
        sender=Endpoint.from_parts("h", 2), configuration_id=5,
        membership_size=4, cell_id=0, cell_size=2,
        parent_configuration_id=777, global_fingerprint=9999,
        global_cells=(0, 1), global_epochs=(10, 12),
        global_sizes=(2, 2), global_leaders=("h:1", "h:3"),
    )
    replies = {"h1:1": a, "h2:2": diverged}
    monkeypatch.setattr(
        statusz, "fetch_status",
        lambda client, target, timeout: replies[
            f"{target.hostname.decode()}:{target.port}"
        ],
    )
    rc = statusz.main(["h1:1", "h2:2"])
    err = capsys.readouterr().err
    assert rc == 2
    assert "composed global view" in err

    # agreement (or one flat member among hierarchical ones) is clean
    replies["h2:2"] = a
    assert statusz.main(["h1:1", "h2:2"]) == 0
    replies["h2:2"] = bare
    assert statusz.main(["h1:1", "h2:2"]) == 0


def test_statusz_config_disagreement_is_cell_scoped(monkeypatch, capsys):
    """In hierarchical mode each cell is its own Rapid cluster, so members
    of different cells legitimately carry different cell-local config ids
    -- statusz must only flag disagreement *within* one cell (and keep the
    flat check for members without a hierarchy digest)."""
    statusz = _load_statusz()

    def member(port, config_id, cell=None):
        kw = {}
        if cell is not None:
            kw = dict(cell_id=cell, cell_size=1, parent_configuration_id=7,
                      global_fingerprint=4242, global_cells=(0, 1),
                      global_epochs=(1, 2), global_sizes=(1, 1),
                      global_leaders=("h:1", "h:2"))
        return ClusterStatusResponse(
            sender=Endpoint.from_parts("h", port),
            configuration_id=config_id, membership_size=1, **kw)

    replies = {}
    monkeypatch.setattr(
        statusz, "fetch_status",
        lambda client, target, timeout: replies[
            f"{target.hostname.decode()}:{target.port}"
        ],
    )
    # cross-cell config divergence with an agreeing composed view: clean
    replies = {"h:1": member(1, 100, cell=0), "h:2": member(2, 200, cell=1)}
    assert statusz.main(["h:1", "h:2"]) == 0
    # same-cell divergence: rc 2, named by cell
    replies = {"h:1": member(1, 100, cell=0), "h:2": member(2, 200, cell=0)}
    assert statusz.main(["h:1", "h:2"]) == 2
    assert "cell 0 configuration id" in capsys.readouterr().err
    # flat members keep the pre-hierarchy check and message
    replies = {"h:1": member(1, 100), "h:2": member(2, 200)}
    assert statusz.main(["h:1", "h:2"]) == 2
    assert "disagree on configuration id" in capsys.readouterr().err
