"""MessagingTest.java analogues: join-phase handling against large (1000-node)
views and broadcaster fan-out at scale (MessagingTest.java:151-182,397-421).
"""

import random

import pytest

from rapid_tpu.cut_detector import MultiNodeCutDetector
from rapid_tpu.membership import MembershipView
from rapid_tpu.messaging.inprocess import (
    InProcessClient,
    InProcessNetwork,
    InProcessServer,
)
from rapid_tpu.messaging.unicast import UnicastToAllBroadcaster
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.runtime.resources import SharedResources
from rapid_tpu.runtime.scheduler import VirtualScheduler
from rapid_tpu.service import MembershipService
from rapid_tpu.settings import Settings
from rapid_tpu.types import (
    Endpoint,
    JoinResponse,
    JoinStatusCode,
    NodeId,
    PreJoinMessage,
    ProbeMessage,
    Response,
)

K, H, L = 10, 9, 4


def ep(i: int) -> Endpoint:
    return Endpoint.from_parts("127.0.0.1", 2000 + i)


def large_view(n: int, seed: int = 1) -> MembershipView:
    rng = random.Random(seed)
    view = MembershipView(K)
    for i in range(n):
        view.ring_add(ep(i), NodeId.random(rng))
    return view


@pytest.fixture
def service_on_large_view():
    scheduler = VirtualScheduler()
    network = InProcessNetwork(scheduler)
    view = large_view(1000)
    addr = ep(0)
    resources = SharedResources(scheduler, name="large-view")
    service = MembershipService(
        addr,
        MultiNodeCutDetector(K, H, L),
        view,
        resources,
        Settings(),
        InProcessClient(addr, network),
        StaticFailureDetectorFactory(set()),
        rng=random.Random(0),
    )
    yield scheduler, view, service
    service.shutdown()
    resources.shutdown()


def test_join_phase1_against_1000_node_view(service_on_large_view):
    """MessagingTest.java:151-182: a pre-join against a 1000-node view answers
    SAFE_TO_JOIN with the correct configuration id and the joiner's K
    expected observers."""
    scheduler, view, service = service_on_large_view
    joiner = Endpoint.from_parts("127.0.0.1", 9999)
    promise = service.handle_message(
        PreJoinMessage(sender=joiner, node_id=NodeId.random(random.Random(42)))
    )
    scheduler.run_for(10)
    response = promise.result(0)
    assert isinstance(response, JoinResponse)
    assert response.status_code == JoinStatusCode.SAFE_TO_JOIN
    assert response.configuration_id == view.get_current_configuration_id()
    assert len(response.endpoints) == K
    assert list(response.endpoints) == view.get_expected_observers_of(joiner)


def test_join_phase1_rejects_present_hostname(service_on_large_view):
    """A pre-join from an endpoint already in the 1000-node ring answers
    HOSTNAME_ALREADY_IN_RING (with observers, for the retry path)."""
    scheduler, view, service = service_on_large_view
    promise = service.handle_message(
        PreJoinMessage(sender=ep(500), node_id=NodeId.random(random.Random(43)))
    )
    scheduler.run_for(10)
    response = promise.result(0)
    assert response.status_code == JoinStatusCode.HOSTNAME_ALREADY_IN_RING
    assert len(response.endpoints) == K


def test_join_phase1_rejects_seen_identifier(service_on_large_view):
    """UUID reuse across the seam answers UUID_ALREADY_IN_RING
    (MembershipView.java:101-116)."""
    scheduler, view, service = service_on_large_view
    # rebuild one of the admitted identifiers
    reused = view.get_configuration().node_ids[17]
    promise = service.handle_message(
        PreJoinMessage(sender=Endpoint.from_parts("10.9.9.9", 1), node_id=reused)
    )
    scheduler.run_for(10)
    assert promise.result(0).status_code == JoinStatusCode.UUID_ALREADY_IN_RING


def test_broadcaster_fanout_100_members():
    """MessagingTest.java:397-421: unicast-to-all reaches every one of 100
    registered members exactly once, in a per-configuration shuffled order."""
    scheduler = VirtualScheduler()
    network = InProcessNetwork(scheduler)
    received = {ep(i): 0 for i in range(100)}

    class CountingServer(InProcessServer):
        def handle(self, msg):
            received[self.address] += 1
            from rapid_tpu.runtime.futures import Promise

            return Promise.completed(Response())

    for i in range(100):
        CountingServer(ep(i), network).start()

    sender = InProcessClient(ep(0), network)
    caster = UnicastToAllBroadcaster(sender, rng=random.Random(1))
    caster.set_membership([ep(i) for i in range(100)])
    promises = caster.broadcast(ProbeMessage(sender=ep(0)))
    assert len(promises) == 100
    scheduler.run_for(10)
    assert all(count == 1 for count in received.values())

    # shuffled per configuration: two broadcasters with different rngs send
    # in different orders over the same membership
    order_a, order_b = [], []
    ca = UnicastToAllBroadcaster(_RecordingClient(order_a), rng=random.Random(2))
    cb = UnicastToAllBroadcaster(_RecordingClient(order_b), rng=random.Random(3))
    members = [ep(i) for i in range(100)]
    ca.set_membership(members)
    cb.set_membership(members)
    ca.broadcast(ProbeMessage(sender=ep(0)))
    cb.broadcast(ProbeMessage(sender=ep(0)))
    assert sorted(order_a) == sorted(order_b) == sorted(members)
    assert order_a != order_b


class _RecordingClient:
    def __init__(self, log):
        self._log = log

    def send_message_best_effort(self, remote, msg):
        from rapid_tpu.runtime.futures import Promise

        self._log.append(remote)
        return Promise.completed(Response())
