"""Classic Paxos recovery in the simulation plane: per-node acceptor state
on device, host-driven coordinator exchange (sim/classic.py).

The scale-out counterpart of tests/test_paxos.py: the same rank-contention
and value-safety properties the object plane pins at tens of nodes
(Paxos.java:97-236,269-326), exercised against device acceptor arrays at
1000+ virtual nodes, including dueling concurrent coordinators.
"""

import numpy as np
import pytest

from rapid_tpu.sim.classic import ClassicCoordinator, make_rank
from rapid_tpu.sim.driver import Simulator
from rapid_tpu.sim.engine import SimConfig


def _stalled_sim(n=1000, n_blind=260, seed=7):
    """A cluster whose fast round genuinely cannot decide: a blind delivery
    class of more than F = floor((N-1)/4) members never hears the alert
    broadcasts, so it never votes and live voters < quorum -- while every
    victim keeps its full live observer set (exact cut) and the live
    majority needed for classic recovery exists."""
    config = SimConfig(capacity=n, groups=2)
    sim = Simulator(n, config=config, seed=seed)
    group_of = np.zeros(n, dtype=np.int32)
    group_of[n - n_blind:] = 1
    sim.set_delivery_groups(group_of)
    victims = np.array([5, 6])
    sim.crash(victims)
    sim.drop_broadcasts(1, np.arange(n))  # group 1 hears nothing at all
    rec = sim.run_until_decision(max_rounds=16, classic_fallback_after_rounds=None)
    assert rec is None, "fast round must stall for these tests"
    announced, proposals = sim.last_announcement
    assert announced[0] and not announced[1]
    np.testing.assert_array_equal(np.flatnonzero(proposals[0]), victims)
    return sim, victims


def test_rank_packing_orders_rounds_then_nodes():
    assert make_rank(2, 0) > (1 << 21 | 1)  # any classic round beats fast
    assert make_rank(2, 5) < make_rank(2, 6) < make_rank(3, 0)


def test_single_coordinator_recovers_stalled_round_at_1k():
    sim, victims = _stalled_sim()
    live = np.flatnonzero(sim.active & sim.alive)
    c = ClassicCoordinator(sim, round_no=2, slot=int(live[0]))
    assert c.phase1()  # 998 live promises > 500
    row = c.pick_value()
    assert row == 0  # the single value at the max (fast) vrnd
    assert c.phase2(row) == 0
    # the decided value is the fast round's proposal: the crashed set
    np.testing.assert_array_equal(
        np.flatnonzero(np.asarray(sim.state.proposal)[row]), victims
    )


def test_dueling_coordinators_interleaved_phase1_at_1k():
    """Two concurrent coordinators in the same round: the higher rank's
    phase1a outranks the lower's promises, the lower coordinator's phase2a
    is rejected by the acceptors, and only the higher decides -- the
    acceptor-side arbitration of Paxos.java:135-145,205-213."""
    sim, victims = _stalled_sim(seed=8)
    live = np.flatnonzero(sim.active & sim.alive)
    c_low = ClassicCoordinator(sim, round_no=2, slot=int(live[0]))
    c_high = ClassicCoordinator(sim, round_no=2, slot=int(live[1]))
    assert c_low.rank < c_high.rank

    assert c_low.phase1()
    assert c_high.phase1()  # re-promises every acceptor at the higher rank
    # the outranked coordinator's phase2 must fail...
    row_low = c_low.pick_value()
    assert c_low.phase2(row_low) is None
    # ...and must not have corrupted acceptor state for the winner
    row_high = c_high.pick_value()
    assert row_high == row_low == 0
    assert c_high.phase2(row_high) == 0
    np.testing.assert_array_equal(
        np.flatnonzero(np.asarray(sim.state.proposal)[0]), victims
    )


def test_late_coordinator_must_choose_the_decided_value_at_1k():
    """Safety across rounds: once a value is chosen, any later coordinator's
    phase1b aggregate reports it at the highest vrnd, and the value-pick
    rule forces re-proposing the same value (Fig. 2 / Paxos.java:269-326)."""
    sim, victims = _stalled_sim(seed=9)
    live = np.flatnonzero(sim.active & sim.alive)
    first = ClassicCoordinator(sim, round_no=2, slot=int(live[0]))
    assert first.phase1()
    decided = first.phase2(first.pick_value())
    assert decided == 0

    late = ClassicCoordinator(sim, round_no=3, slot=int(live[5]))
    assert late.phase1()
    # every live acceptor reports vval=0 at vrnd=first.rank (the max)
    assert int(late._summary.max_vrnd) == first.rank
    assert late.pick_value() == decided
    assert late.phase2(late.pick_value()) == decided


def test_no_valid_vote_means_no_phase2():
    """A quorum of never-voted acceptors yields no vval: the coordinator
    must not proceed (Paxos.java:311-326 comment) -- nothing is invented."""
    sim = Simulator(40, seed=11)  # healthy cluster: nobody ever voted
    c = ClassicCoordinator(sim, round_no=2, slot=0)
    assert c.phase1()
    assert c.pick_value() is None


def test_conflicting_fast_votes_pick_the_quarter_majority_value_at_1k():
    """Diverging fast votes (two delivery groups proposing different cuts):
    the rule's middle clause picks the value with more than N/4 votes at the
    max vrnd."""
    n = 1000
    config = SimConfig(capacity=n, groups=2)
    sim = Simulator(n, config=config, seed=12)
    group_of = np.zeros(n, dtype=np.int32)
    group_of[700:] = 1  # 300-member minority class
    sim.set_delivery_groups(group_of)
    victims = np.array([10, 11])
    sim.crash(victims)
    # the minority group misses alerts about victim 11: it proposes {10}
    # while the majority proposes {10, 11} -- real proposal divergence
    sim.drop_broadcasts(1, np.asarray(sim.state.observers)[11])
    rec = sim.run_until_decision(max_rounds=20, classic_fallback_after_rounds=None)
    if rec is not None:
        pytest.skip("fault plane did not produce divergence for this seed")
    live = np.flatnonzero(sim.active & sim.alive)
    c = ClassicCoordinator(sim, round_no=2, slot=int(live[0]))
    assert c.phase1()
    row = c.pick_value()
    proposals = np.asarray(sim.state.proposal)
    # the chosen value is the one with > N/4 = 250 votes: the majority cut
    np.testing.assert_array_equal(np.flatnonzero(proposals[row]), victims)
    assert c.phase2(row) == row


def test_driver_fallback_uses_device_exchange_at_1k():
    """End-to-end through run_until_decision: the stalled fast round recovers
    via the device classic exchange, bills the four hops, and applies the
    correct cut."""
    sim, victims = _stalled_sim(seed=13)
    rec = sim.run_until_decision(max_rounds=16, classic_fallback_after_rounds=2)
    assert rec is not None and rec.via_classic_round
    np.testing.assert_array_equal(np.sort(rec.cut), victims)
    assert sim.membership_size == 998
    # acceptor state persisted on device through the exchange is reset with
    # the new configuration
    assert int(np.asarray(sim.state.classic_rnd).max()) == 0


def test_phase1_pools_identical_values_across_rows():
    """A value's phase1b votes pool across proposal rows holding the same
    cut -- a group row and an extern row interned from real members' votes
    (register_extern_vote) -- exactly like the fast tally's equality pooling;
    the reference keys its phase1b counters by value, not by row
    (Paxos.java:276-306)."""
    n = 1000
    config = SimConfig(capacity=n, groups=2, extern_proposals=2)
    sim = Simulator(n, config=config, seed=21)
    group_of = np.zeros(n, dtype=np.int32)
    group_of[n - 260:] = 1
    sim.set_delivery_groups(group_of)
    victims = np.array([5, 6])
    sim.crash(victims)
    sim.drop_broadcasts(1, np.arange(n))
    rec = sim.run_until_decision(max_rounds=16, classic_fallback_after_rounds=None)
    assert rec is None
    # ten blind-group members (who never heard the alerts, hence never voted)
    # vote the same cut through the extern path, as bridged real nodes would
    blind = np.flatnonzero((group_of == 1) & sim.active & sim.alive)[:10]
    for slot in blind:
        sim.auto_vote[int(slot)] = False
        assert sim.register_extern_vote(int(slot), victims)
    # the partition heals before recovery: classic traffic rides the same
    # delivery fault plane as broadcasts, so a group-0 coordinator could not
    # hear the blind group's phase1b responses while the drop was active
    sim.clear_link_faults()
    live = np.flatnonzero(sim.active & sim.alive)
    c = ClassicCoordinator(sim, round_no=2, slot=int(live[0]))
    assert c.phase1()
    at_max = np.asarray(c._summary.at_max)
    rep = np.asarray(c._summary.rep)
    extern_row = 2  # first extern row (after the 2 group rows)
    # 738 group-0 voters + 10 extern voters pool into one value of 748
    assert at_max[0] == at_max[extern_row] == 748
    assert rep[extern_row] == 0  # canonical row of the shared value
    assert c.pick_value() == 0
    assert c.phase2(0) == 0


def test_driver_races_concurrent_coordinators(monkeypatch):
    """Driver-level fallback race (VERDICT r2 item 7): two nodes' expovariate
    timers fire within a round of each other, so the driver runs their
    coordinators CONCURRENTLY -- the later, higher-ranked one steals the
    quorum mid-exchange and the round still converges on the announced cut,
    with safety arbitrated by the shared device acceptor state."""
    sim, victims = _stalled_sim(seed=17)
    n_live = int((sim.active & sim.alive).sum())

    class RiggedRng:
        def exponential(self, scale, size):
            t = np.full(size, 1_000_000.0)
            t[0] = 0.2  # first timer
            t[1] = 0.6  # second fires within one round: a genuine race
            return t

    monkeypatch.setattr(sim, "_host_rng", RiggedRng())
    rec = sim.run_until_decision(max_rounds=8, classic_fallback_after_rounds=2)
    assert rec is not None and rec.via_classic_round
    np.testing.assert_array_equal(np.sort(rec.cut), victims)
    assert sim.metrics.get("classic_coordinator_races") == 1
    assert sim.membership_size == n_live  # victims were already dead, now cut


def test_recovery_traffic_rides_delivery_fault_plane():
    """A coordinator whose own group hears nobody cannot manufacture a
    decision: its phase1b inbox stays empty even though acceptors heard and
    promised to its phase1a (lost responses still advance acceptor state,
    like lost gRPC responses in the reference)."""
    from rapid_tpu.sim.classic import make_rank

    n = 400
    config = SimConfig(capacity=n, groups=2)
    sim = Simulator(n, config=config, seed=23)
    group_of = np.zeros(n, dtype=np.int32)
    group_of[0] = 1  # the deaf coordinator's own group
    sim.set_delivery_groups(group_of)
    victims = np.array([7])
    sim.crash(victims)
    sim.run_until_decision(max_rounds=4, classic_fallback_after_rounds=None)
    sim.drop_broadcasts(1, np.arange(n))  # group 1 hears nothing
    deaf = ClassicCoordinator(sim, round_no=2, slot=0)
    assert not deaf.phase1()  # no audible phase1b majority
    # but the acceptors it reached did promise: a later, lower-ranked
    # coordinator cannot win them back
    rnd = np.asarray(sim.state.classic_rnd)
    assert (rnd >= make_rank(2, 0)).sum() > n // 2


def test_driver_race_later_arrival_outranked(monkeypatch):
    """The other interleaving: the FIRST timer to fire belongs to a higher
    slot, so the later coordinator is outranked (rank = (round, slot), slot
    breaks the tie like the reference's address hash) -- its phase1 wins no
    quorum and the earlier, higher-ranked coordinator decides."""
    sim, victims = _stalled_sim(seed=19)

    class RiggedRng:
        def exponential(self, scale, size):
            t = np.full(size, 1_000_000.0)
            t[9] = 0.2  # higher slot fires FIRST
            t[0] = 0.6  # lower slot races, arrives second, is outranked
            return t

    monkeypatch.setattr(sim, "_host_rng", RiggedRng())
    rec = sim.run_until_decision(max_rounds=8, classic_fallback_after_rounds=2)
    assert rec is not None and rec.via_classic_round
    np.testing.assert_array_equal(np.sort(rec.cut), victims)
    assert sim.metrics.get("classic_coordinator_races") == 1


def test_extern_vote_refused_after_classic_participation():
    """register_extern_vote applies the registerFastRoundVote gate
    (Paxos.java:246-248): a slot that promised in a classic round cannot have
    a fast vote counted toward a fast quorum."""
    sim, victims = _stalled_sim(seed=22)
    live = np.flatnonzero(sim.active & sim.alive)
    c = ClassicCoordinator(sim, round_no=2, slot=int(live[0]))
    assert c.phase1()  # every live group-0 acceptor promised at a classic rank
    promised = int(live[3])
    sim.auto_vote[promised] = False
    assert not sim.register_extern_vote(promised, victims)
