"""Failure-detector policy knobs and cross-plane parity.

The object plane (Settings.fd_policy -> PingPong/WindowedPingPong detectors)
and the sim plane (SimConfig.fd_policy -> engine cumulative/windowed phases)
expose the same two policies with the same parameters; a shared probe-outcome
script must trip both at the same probe index (paper section 6's "40% of the
last 10" vs the reference code's cumulative counter).
"""

import numpy as np

from rapid_tpu import ClusterBuilder, Endpoint, Settings
from rapid_tpu.monitoring.pingpong import (
    PingPongFailureDetector,
    PingPongFailureDetectorFactory,
    WindowedPingPongFailureDetector,
    WindowedPingPongFailureDetectorFactory,
)
from rapid_tpu.runtime.futures import Promise
from rapid_tpu.sim.driver import Simulator
from rapid_tpu.sim.engine import SimConfig
from rapid_tpu.types import ProbeResponse


class ScriptedClient:
    """Probe outcomes from a script: True = probe succeeds, False = fails."""

    def __init__(self, script):
        self.script = list(script)
        self.sent = 0

    def send_message_best_effort(self, remote, msg):
        ok = self.script[self.sent]
        self.sent += 1
        if ok:
            return Promise.completed(ProbeResponse())
        return Promise.failed(ConnectionError("scripted probe loss"))


def object_plane_first_failure(script, make_fd):
    """Tick the detector once per script entry; the probe index at which
    has_failed() first turns true (None if never)."""
    client = ScriptedClient(script)
    fd = make_fd(client)
    for t in range(len(script)):
        fd()
        if fd.has_failed():
            return t
    return None


def sim_plane_first_alert(script, fd_policy, window=10, threshold=0.4):
    """Run one engine round per script entry, toggling the victim's ingress
    partition per the script; the round index at which the victim's observer
    edges first alert (None if never)."""
    n = 16
    config = SimConfig(
        capacity=n, fd_policy=fd_policy, fd_window=window,
        fd_window_threshold=threshold,
    )
    sim = Simulator(n, config=config, seed=3)
    victim = 4
    observers = np.asarray(sim.state.observers)  # [C, K] observer ids per dst
    for t, ok in enumerate(script):
        if ok:
            sim.clear_link_faults()
        else:
            sim.one_way_ingress_partition(np.array([victim]))
        sim.run_until_decision(max_rounds=1, batch=1,
                               classic_fallback_after_rounds=None)
        alerted = np.asarray(sim.state.alerted)  # [C, K] by observer
        # edges from the victim's observers toward it
        subj = np.asarray(sim.state.subjects)
        from_observers = alerted[observers[victim], :]
        hit = [
            bool(alerted[int(o), k])
            for k in range(config.k)
            for o in [observers[victim, k]]
            if subj[int(o), k] == victim
        ]
        if any(hit):
            return t
    return None


# fail-heavy tail after a clean start: cumulative trips at the 10th failure,
# windowed trips when 4 of the last 10 probes failed
SCRIPT = [True] * 6 + [False, True, False, True] * 12


def test_cross_plane_windowed_parity():
    obj = object_plane_first_failure(
        SCRIPT,
        lambda client: WindowedPingPongFailureDetector(
            Endpoint.from_parts("a", 1), Endpoint.from_parts("b", 2),
            client, lambda: None, window=10, threshold=0.4,
        ),
    )
    sim = sim_plane_first_alert(SCRIPT, "windowed")
    assert obj is not None and sim is not None
    assert obj == sim, f"object plane fired at {obj}, sim plane at {sim}"


def test_cross_plane_cumulative_parity():
    obj = object_plane_first_failure(
        SCRIPT,
        lambda client: PingPongFailureDetector(
            Endpoint.from_parts("a", 1), Endpoint.from_parts("b", 2),
            client, lambda: None,
        ),
    )
    sim = sim_plane_first_alert(SCRIPT, "cumulative")
    assert obj is not None and sim is not None
    assert obj == sim, f"object plane fired at {obj}, sim plane at {sim}"


def test_settings_select_fd_policy():
    """ClusterBuilder wires the windowed detector from Settings alone
    (VERDICT r2 item 9: constructor injection is no longer the only path)."""
    addr = Endpoint.from_parts("127.0.0.1", 9551)
    client = ScriptedClient([True] * 4)

    builder = ClusterBuilder(addr).use_settings(
        Settings(fd_policy="windowed", fd_window=7, fd_window_threshold=0.5)
    )
    factory = builder._fd(client)
    assert isinstance(factory, WindowedPingPongFailureDetectorFactory)
    fd = factory.create_instance(Endpoint.from_parts("b", 2), lambda: None)
    assert fd._window.maxlen == 7 and fd._threshold == 0.5

    builder = ClusterBuilder(addr).use_settings(Settings(fd_failure_threshold=3))
    factory = builder._fd(ScriptedClient([False] * 4))
    assert isinstance(factory, PingPongFailureDetectorFactory)
    fd = factory.create_instance(Endpoint.from_parts("b", 2), lambda: None)
    for _ in range(4):
        fd()
    assert fd.has_failed()  # 3 failed probes suffice under the knob
