"""Multi-host execution across REAL OS processes (VERDICT r3 item 2 / missing
#1): ``jax.distributed.initialize`` on the CPU backend wires two processes
(each owning two forced CPU devices) into one global runtime;
``make_multihost_mesh`` groups the global devices by owning process into
("dcn", "ici") rows, and the full sharded driver -- early-exit while_loop,
cross-process pmax collective, view change -- runs the same SPMD program in
both processes. This executes the process-grouped DCN-row logic and the
cross-process collective for real, not in their degenerate single-process
form.

The assertion is bit-identity three ways: both processes report the same
record, and it equals a single-process run of the identical scenario on a
local (2, 2) mesh.
"""

import os
import re
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
EXAMPLE = REPO / "examples" / "multihost_sim.py"

N = 256
SEED = 42
_RECORD = re.compile(
    r"cut (\d+) nodes in (\d+) ms protocol time .*; config (-?\d+)"
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(tmp_path, pid: int, port: int, num_processes: int,
            devices: int) -> subprocess.Popen:
    log = open(tmp_path / f"proc-{pid}.log", "w")
    cmd = [
        sys.executable, str(EXAMPLE),
        "--coordinator", f"127.0.0.1:{port}",
        "--num-processes", str(num_processes),
        "--process-id", str(pid),
        "--cpu-devices-per-host", str(devices),
        "--n", str(N),
        "--seed", str(SEED),
    ]
    return subprocess.Popen(
        cmd, stdout=log, stderr=subprocess.STDOUT,
        env=dict(os.environ, PYTHONUNBUFFERED="1"), cwd=str(REPO),
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "num_processes,devices_per_host",
    [
        (2, 2),  # the minimum nontrivial shape: 2 hosts x 2 chips
        (4, 1),  # more hosts, single chip each: every DCN row is one process
    ],
)
def test_sharded_driver_bit_identical_across_real_processes(
    tmp_path, num_processes, devices_per_host
):
    port = _free_port()
    procs = [
        _launch(tmp_path, pid, port, num_processes, devices_per_host)
        for pid in reversed(range(num_processes))
    ]
    try:
        for p in procs:
            assert p.wait(timeout=360) == 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    records = []
    for pid in range(num_processes):
        text = (tmp_path / f"proc-{pid}.log").read_text()
        assert (
            f"mesh {{'dcn': {num_processes}, 'ici': {devices_per_host}}}"
            in text
        ), text
        m = _RECORD.search(text)
        assert m, f"no record line in process {pid}'s output:\n{text}"
        records.append(tuple(int(g) for g in m.groups()))
    assert len(set(records)) == 1, f"processes diverged: {records}"
    cut_len, virtual_ms, config_id = records[0]

    # the same scenario single-process on a local mesh of the same shape:
    # the global program is identical, so the record must match bit for bit
    from rapid_tpu.shard.engine import make_mesh
    from rapid_tpu.sim.driver import Simulator

    sim = Simulator(
        N, seed=SEED, mesh=make_mesh(shape=(num_processes, devices_per_host))
    )
    rng = np.random.default_rng(SEED)
    victims = rng.choice(N, max(1, int(N * 0.01)), replace=False)
    sim.crash(victims)
    rec = sim.run_until_decision(max_rounds=16, batch=16)
    assert rec is not None and set(rec.cut) == set(victims)
    assert len(rec.cut) == cut_len
    assert rec.virtual_time_ms == virtual_ms
    assert rec.configuration_id == config_id


@pytest.mark.slow
def test_uneven_devices_per_process_fails_loudly(tmp_path):
    """Heterogeneous hosts (2 devices vs 1) cannot form a ('dcn', 'ici')
    mesh; make_multihost_mesh must refuse with a message naming the per-
    process widths and the chips_per_host escape hatch -- not collapse into
    a ragged-array Mesh error."""
    port = _free_port()
    log0 = open(tmp_path / "uneven-0.log", "w")
    log1 = open(tmp_path / "uneven-1.log", "w")
    cmds = []
    for pid, devices, log in ((0, 2, log0), (1, 1, log1)):
        cmds.append(subprocess.Popen(
            [
                sys.executable, str(EXAMPLE),
                "--coordinator", f"127.0.0.1:{port}",
                "--num-processes", "2",
                "--process-id", str(pid),
                "--cpu-devices-per-host", str(devices),
                "--n", str(N),
                "--seed", str(SEED),
            ],
            stdout=log, stderr=subprocess.STDOUT,
            env=dict(os.environ, PYTHONUNBUFFERED="1"), cwd=str(REPO),
        ))
    try:
        rcs = [p.wait(timeout=360) for p in cmds]
    finally:
        for p in cmds:
            if p.poll() is None:
                p.kill()
    assert all(rc != 0 for rc in rcs), f"uneven shape was accepted: {rcs}"
    combined = (
        (tmp_path / "uneven-0.log").read_text()
        + (tmp_path / "uneven-1.log").read_text()
    )
    assert "uneven devices per process" in combined, combined
