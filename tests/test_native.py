"""Native (C++) control plane: bit-exactness against the pure-Python xxHash64
and the numpy adjacency builder. Skipped when no toolchain can build the
library (the framework falls back to numpy everywhere).
"""

import random

import numpy as np
import pytest

from rapid_tpu import native
from rapid_tpu.hashing import endpoint_hash_batch, pack_hostnames, xxh64

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no toolchain?)"
)


def test_native_xxh64_bit_exact():
    rng = random.Random(3)
    samples = [bytes(rng.randrange(256) for _ in range(n)) for n in range(0, 80)]
    data, lengths = pack_hostnames(samples)
    for seed in (0, 5, 2**31 - 1, 2**64 - 3):
        out = native.xxh64_batch(data, lengths, seed)
        ref = np.array([xxh64(s, seed) for s in samples], dtype=np.uint64)
        assert np.array_equal(out, ref)


def test_native_ring_hashes_match_numpy():
    hosts = [f"host-{i}.example".encode() for i in range(500)]
    ports = np.arange(500, dtype=np.int64) + 4000
    data, lengths = pack_hostnames(hosts)
    out = native.ring_hashes(data, lengths, ports, 10)
    ref = np.stack([endpoint_hash_batch(data, lengths, ports, k) for k in range(10)])
    assert np.array_equal(out, ref)


def test_native_adjacency_matches_numpy_filter_path():
    """The C++ sort-based adjacency builder must agree with the cached-order
    numpy filter path that topology.build_adjacency uses by default."""
    from rapid_tpu.sim.topology import VirtualCluster, build_adjacency

    vc = VirtualCluster.synthesize(300, 10, seed=6)
    rng = np.random.default_rng(1)
    active = rng.random(300) < 0.8
    np_subjects, np_observers = build_adjacency(vc, active)
    nat = native.build_adjacency(vc.ring_hashes, active)
    assert nat is not None
    assert np.array_equal(nat[0], np_subjects)
    assert np.array_equal(nat[1], np_observers)


def test_adjacency_matches_membership_view():
    """End to end through VirtualCluster: adjacency must match the
    object-model MembershipView."""
    from rapid_tpu.membership import MembershipView
    from rapid_tpu.sim.topology import VirtualCluster, build_adjacency
    from rapid_tpu.types import Endpoint, NodeId

    k = 10
    vc = VirtualCluster.synthesize(40, k, seed=4)
    active = np.ones(40, dtype=bool)
    active[[3, 12]] = False
    subjects, observers = build_adjacency(vc, active)

    view = MembershipView(k)
    eps = []
    for i in range(40):
        host = bytes(vc.hostnames[i, : vc.host_lengths[i]])
        eps.append(Endpoint(host, int(vc.ports[i])))
        if active[i]:
            view.ring_add(eps[i], NodeId(int(vc.id_high[i]), int(vc.id_low[i])))
    for i in np.flatnonzero(active):
        assert [eps[s] for s in subjects[i]] == view.get_subjects_of(eps[i])
        assert [eps[o] for o in observers[i]] == view.get_observers_of(eps[i])
    # inactive rows stay self-loops
    assert (subjects[3] == 3).all() and (observers[12] == 12).all()


def test_config_fold_matches_python():
    lib = native.load()
    xs = np.array([5, 2**63 + 7, 12345678901234567], dtype=np.uint64)
    h = 1
    for x in xs:
        h = (h * 37 + int(x)) & (2**64 - 1)
    assert int(lib.rapid_config_fold(xs, len(xs))) == h


def test_native_config_fold_matches_numpy():
    """The C fold and the vectorized power-ladder formula agree bit-exactly."""
    from rapid_tpu import native
    from rapid_tpu.sim.topology import _powers_of_37

    rng = np.random.default_rng(3)
    for m in (0, 1, 7, 1000):
        xs = rng.integers(0, 2**64, size=m, dtype=np.uint64)
        got = native.config_fold(xs)
        with np.errstate(over="ignore"):
            pw = _powers_of_37(m)
            want = int(
                (pw[m] + (xs * pw[:m][::-1]).sum(dtype=np.uint64)).astype(np.int64)
            )
        assert got == want
