"""The SLO plane: online SLIs, multi-window burn-rate alerts, and
churn-episode attribution (ISSUE 17).

What is pinned here:

- burn-rate arithmetic at window edges: exact burn values the moment a
  bucket enters/leaves a trailing window, the both-windows fire rule, and
  clear hysteresis (fire -> hover -> clear);
- virtual-vs-wall parity: the same request pattern fed through a
  wall-scale plane and a ``window_scale`` compressed plane produces the
  same burns and the same alert transitions -- the arithmetic is
  scale-invariant by construction;
- the SLI primitives: histogram_quantile merge semantics, SliTracker
  window edges, the open-loop generator's rebase contract;
- attribution: journal tails fold into episodes, burn windows attribute
  to the largest-overlap episode, and describe() renders the operator
  line;
- check_metastable_recovery: vacuous by design on thin/degraded
  baselines, and a seeded kill-test proving it bites on a history that
  never recovers after the faults clear;
- the wire: the four SLO digest fields round-trip both transports and a
  kill-switch-off node reports none of them;
- the service: a live cluster with ``settings.slo.enabled`` answers
  status probes with the alert digest; default settings reproduce the
  exact pre-SLO (empty) surface.
"""

import json

import pytest

from harness import ClusterHarness

from rapid_tpu import Endpoint, InMemoryPartitionStore, Settings
from rapid_tpu.messaging import grpc_transport as gt
from rapid_tpu.messaging.codec import decode, encode
from rapid_tpu.messaging.inprocess import InProcessClient
from rapid_tpu.messaging.wire_schema import MSG
from rapid_tpu.search.checkers import (
    ClientOp,
    InvariantViolation,
    check_metastable_recovery,
    goodput_samples,
)
from rapid_tpu.settings import SLOSettings
from rapid_tpu.slo import (
    BURN_WINDOWS,
    SLI_CATALOG,
    SLO_CATALOG,
    BurnRateEngine,
    OpenLoopGenerator,
    SliTracker,
    SloPlane,
    attribute_burn,
    describe,
    episodes_from_journal,
    histogram_quantile,
)
from rapid_tpu.types import ClusterStatusRequest, ClusterStatusResponse, PutAck

# ---------------------------------------------------------------------------
# burn-rate arithmetic at window edges
# ---------------------------------------------------------------------------

# one tiny window pair so every edge is hand-computable: short 1 s,
# long 2 s, fire at 2x budget burn
EDGE_WINDOWS = {"w": {"short_s": 1, "long_s": 2, "burn": 2.0}}
EDGE_SPEC = {"sli": "availability", "objective": 0.9, "windows": ("w",)}


def _edge_engine(clear_fraction=0.5):
    tracker = SliTracker(bucket_ms=100, predicates=("availability",))
    engine = BurnRateEngine(
        "edge", EDGE_SPEC, tracker,
        window_scale=1.0, clear_fraction=clear_fraction,
        windows=EDGE_WINDOWS,
    )
    return tracker, engine


def test_burn_rate_exact_at_window_edges():
    """Exact arithmetic pins: 2 bad + 8 good in the short window is error
    rate 0.2 over budget 0.1 = burn 2.0, and the bad bucket falls out of
    the short window on exactly the tick its bucket stops overlapping
    ``(now - short, now]``."""
    tracker, engine = _edge_engine()
    for t in (0, 10):  # bucket [0, 100): the bad ones
        tracker.record(t, 1.0, [])
    for i in range(8):  # bucket [500, 600): the good ones
        tracker.record(500 + i, 1.0, ["availability"])

    assert engine.burn_rate(1000, 1000) == pytest.approx(2.0)
    assert engine.burn_rate(1000, 2000) == pytest.approx(2.0)
    transitions = engine.tick(1000)
    alert = engine.alerts[0]
    assert [(k, a.name) for k, a in transitions] == [("fired", "edge:w")]
    assert alert.burn_short == pytest.approx(2.0)
    assert alert.burn_long == pytest.approx(2.0)
    assert alert.peak_burn == pytest.approx(2.0)

    # now=1100: cutoff is 100, the bad bucket [0,100) no longer overlaps
    # the short window -- burn_short drops to exactly 0 while the long
    # window still carries the full error mass
    assert engine.tick(1100) == []  # long window holds it firing
    assert alert.burn_short == pytest.approx(0.0)
    assert alert.burn_long == pytest.approx(2.0)
    assert alert.firing

    # now=2100: the bad bucket leaves the long window too -> both clear
    transitions = engine.tick(2100)
    assert [(k, a.name) for k, a in transitions] == [("cleared", "edge:w")]
    assert not alert.firing
    assert alert.cleared_at_ms == 2100
    assert alert.fired_count == 1


def test_burn_alert_fires_only_when_both_windows_burn():
    """The multi-window rule: a short-window spike with a quiet long
    window never pages."""
    tracker, engine = _edge_engine()
    # long window: 38 good spread over [0, 1000)
    for i in range(38):
        tracker.record(i * 26, 1.0, ["availability"])
    # short spike: 2 bad in [1900, 2000) -- the only traffic in the short
    # window, so burn_short = (1.0 / 0.1) = 10x ...
    tracker.record(1900, 1.0, [])
    tracker.record(1910, 1.0, [])
    engine.tick(2000)
    alert = engine.alerts[0]
    assert alert.burn_short == pytest.approx(10.0)
    # ... but the long window dilutes it: 2 bad / 40 total = 0.05 error
    assert alert.burn_long == pytest.approx(0.5)
    assert not alert.firing  # no page: not sustained


def test_burn_alert_clear_hysteresis_no_flap():
    """A burn hovering just under the threshold cannot flap the alert:
    clearing requires BOTH windows under clear_fraction x threshold."""
    tracker, engine = _edge_engine(clear_fraction=0.9)  # clear under 1.8x
    for i in range(8):
        tracker.record(i, 1.0, [])  # 8 bad
    for i in range(32):
        tracker.record(100 + i, 1.0, ["availability"])  # 32 good
    engine.tick(500)  # error 8/40 = 0.2 -> burn 2.0 on both windows
    alert = engine.alerts[0]
    assert alert.firing

    # hover: fresh bucket at 19% errors -> burn 1.9, above the 1.8 clear
    # line but below the 2.0 fire line -- must stay firing, not flap
    for i in range(81):
        tracker.record(1200 + i, 1.0, ["availability"])
    for i in range(19):
        tracker.record(1300 + i, 1.0, [])
    engine.tick(2100)  # short window = only the hover bucket
    assert alert.burn_short == pytest.approx(1.9)
    assert alert.firing
    assert alert.fired_count == 1  # never cleared, never re-fired

    # full recovery: clean traffic only -> both burns 0 -> clears
    for i in range(10):
        tracker.record(3600 + i, 1.0, ["availability"])
    engine.tick(4400)
    assert not alert.firing


def test_burn_windows_scale_invariant_virtual_vs_wall():
    """Virtual-vs-wall parity: the identical request pattern fed at wall
    scale and at 1000x compression (window_scale=0.001, bucket_ms scaled
    the same way) produces identical burns, transitions, and summaries."""
    wall = SloPlane(SLOSettings(enabled=True, bucket_ms=1000,
                                window_scale=1.0))
    virt = SloPlane(SLOSettings(enabled=True, bucket_ms=1,
                                window_scale=0.001))

    def feed(plane, scale_ms):
        # 60 "seconds" of clean traffic, then 120 of 50% errors (record()
        # ticks the engines itself, once per SLI bucket)
        for s in range(60):
            plane.record_offered(int(s * scale_ms))
            plane.record(int(s * scale_ms), True, 2.0)
        for s in range(60, 180):
            t = int(s * scale_ms)
            plane.record_offered(t)
            plane.record(t, s % 2 == 0, 2.0)
        return int(180 * scale_ms)

    wall_now = feed(wall, 1000.0)
    virt_now = feed(virt, 1.0)
    assert sum(a.fired_count for a in wall.alerts()) >= 1  # the burn bit
    for wall_a, virt_a in zip(wall.alerts(), virt.alerts()):
        assert wall_a.name == virt_a.name
        assert wall_a.firing == virt_a.firing
        assert wall_a.fired_count == virt_a.fired_count
        assert wall_a.burn_short == pytest.approx(virt_a.burn_short)
        assert wall_a.burn_long == pytest.approx(virt_a.burn_long)
        assert wall_a.peak_burn == pytest.approx(virt_a.peak_burn)
    wall_sum = wall.summary(wall_now)
    virt_sum = virt.summary(virt_now)
    for name in SLO_CATALOG:
        assert wall_sum[name]["availability"] == pytest.approx(
            virt_sum[name]["availability"]
        )
        assert wall_sum[name]["peak_burn"] == pytest.approx(
            virt_sum[name]["peak_burn"]
        )


# ---------------------------------------------------------------------------
# SLI primitives
# ---------------------------------------------------------------------------


def test_histogram_quantile_merge_semantics():
    edges = (1.0, 5.0, 25.0)
    # counts per le-edge plus the +Inf slot
    assert histogram_quantile(edges, (0, 0, 0, 0), 0.99) == 0.0
    assert histogram_quantile(edges, (10, 0, 0, 0), 0.99) == 1.0
    assert histogram_quantile(edges, (5, 4, 1, 0), 0.5) == 1.0
    assert histogram_quantile(edges, (5, 4, 1, 0), 0.99) == 25.0
    assert histogram_quantile(edges, (0, 0, 0, 3), 0.5) == float("inf")
    # mergeability: summing two nodes' counts quantiles like one node
    a, b = (5, 4, 1, 0), (0, 0, 9, 1)
    merged = tuple(x + y for x, y in zip(a, b))
    assert histogram_quantile(edges, merged, 0.9) == 25.0


def test_sli_tracker_window_edges_and_goodput():
    tracker = SliTracker(bucket_ms=100, predicates=("availability",))
    tracker.record_offered(150, 3)  # 3 offered, only 2 ever complete
    tracker.record(150, 2.0, ["availability"])
    tracker.record(199, 30.0, [])
    w = tracker.window(1000, 1000)
    assert (w.total, w.offered) == (2, 3)
    assert w.availability("availability") == pytest.approx(0.5)
    assert w.goodput_ratio("availability") == pytest.approx(1 / 3)
    # the bucket [100,200) leaves a 1000ms window exactly at now=1200
    assert tracker.window(1199, 1000).total == 2
    assert tracker.window(1200, 1000).total == 0
    # empty windows consume no budget and claim full goodput
    empty = tracker.window(5000, 100)
    assert empty.availability("availability") == 1.0
    assert empty.goodput_ratio() == 1.0
    assert empty.quantile(0.99) == 0.0


def test_open_loop_generator_rebase_forward_only():
    gen = OpenLoopGenerator(1000.0, [b"k"], seed=3)
    first = gen.arrivals(5)
    gen.rebase(10_000)
    jumped = gen.next_arrival()
    assert jumped.at_ms >= 10_000
    gen.rebase(0)  # backward rebase is a no-op: arrivals stay monotone
    nxt = gen.next_arrival()
    assert nxt.at_ms >= jumped.at_ms
    assert [a.at_ms for a in first] == sorted(a.at_ms for a in first)


# ---------------------------------------------------------------------------
# attribution
# ---------------------------------------------------------------------------


def _journal():
    return [
        {"kind": "fd_signal", "virtual_ms": 1000,
         "detail": {"trace_id": 7}},
        {"kind": "placement_rebalance", "virtual_ms": 1500,
         "detail": {"configuration_id": 5, "moved": 41}},
        {"kind": "view_install", "virtual_ms": 1600, "node": "n1",
         "detail": {"trace_id": 7, "removed": 3, "added": 0,
                    "configuration_id": 5}},
    ]


def test_episodes_fold_signal_install_and_rebalance():
    episodes = episodes_from_journal(_journal())
    assert len(episodes) == 1
    ep = episodes[0]
    assert (ep.kind, ep.trace_id) == ("view-change", 7)
    assert (ep.start_ms, ep.end_ms) == (1000, 1600)
    assert ep.nodes_evicted == 3 and ep.partitions_moved == 41
    assert describe(ep) == (
        "view-change episode 7 (3 nodes evicted, 41 partitions moved)"
    )
    # the JSON-line wire dialect parses identically
    as_lines = [json.dumps(e) for e in _journal()]
    assert episodes_from_journal(as_lines) == episodes


def test_episodes_in_flight_and_recovery():
    episodes = episodes_from_journal([
        {"kind": "fd_signal", "virtual_ms": 900, "detail": {"trace_id": 3}},
        {"kind": "durability_recovered", "virtual_ms": 400,
         "detail": {"node": "n2"}},
    ])
    kinds = {e.kind: e for e in episodes}
    assert kinds["view-change"].trace_id == 3
    assert kinds["view-change"].end_ms == 900  # install not landed yet
    assert describe(kinds["recovery"]) == "recovery replay on n2"


def test_attribute_burn_largest_overlap_later_start_wins():
    episodes = episodes_from_journal(_journal() + [
        {"kind": "view_install", "virtual_ms": 5000,
         "detail": {"trace_id": 9, "removed": 1, "configuration_id": 6}},
    ])
    # a burn window spanning both: episode 7 overlaps 600ms, 9 only 1ms
    assert attribute_burn(episodes, 500, 4000).trace_id == 7
    # a window over neither
    assert attribute_burn(episodes, 10_000, 11_000) is None
    # equal overlap (both instantaneous inside): later start wins
    tie = episodes_from_journal([
        {"kind": "view_install", "virtual_ms": 100, "detail": {"trace_id": 1}},
        {"kind": "view_install", "virtual_ms": 200, "detail": {"trace_id": 2}},
    ])
    assert attribute_burn(tie, 0, 300).trace_id == 2
    assert describe(None).startswith("unattributed")


def test_plane_attributes_fired_alert_to_episode():
    plane = SloPlane(SLOSettings(enabled=True, bucket_ms=1,
                                 window_scale=0.001))
    for t in range(0, 400):  # sustained total outage: everything fires
        plane.record_offered(t)
        plane.record(t, False, 500.0)
        plane.tick(t, force=True)
    assert plane.firing_count() >= 2
    plane.attribute([
        {"kind": "fd_signal", "virtual_ms": 50, "detail": {"trace_id": 7}},
        {"kind": "view_install", "virtual_ms": 120, "node": "n0",
         "detail": {"trace_id": 7, "removed": 1, "configuration_id": 2}},
    ])
    names, burns, firing, traces = plane.status_digest()
    assert set(names) == {
        f"{slo}:{w}" for slo in SLO_CATALOG for w in ("fast", "slow")
    }
    for name, burn, fire, trace in zip(names, burns, firing, traces):
        if fire:
            assert trace == 7, f"{name} fired unattributed"
            assert burn > 0


# ---------------------------------------------------------------------------
# the metastable-recovery checker
# ---------------------------------------------------------------------------


def _op(invoke_ms, status=PutAck.STATUS_OK, op="put"):
    return ClientOp(client="c", op=op, key=b"k", value=b"v", version=1,
                    status=status, invoke_ms=invoke_ms,
                    complete_ms=invoke_ms + 1)


def test_metastable_recovery_passes_when_goodput_returns():
    history = (
        [_op(t) for t in range(0, 200, 10)]              # clean baseline
        + [_op(t, PutAck.STATUS_RETRY) for t in range(1000, 1100, 10)]
        + [_op(t) for t in range(3000, 3200, 10)]        # full recovery
    )
    check_metastable_recovery(
        history, faulted_from_ms=1000, healed_at_ms=3000
    )


def test_metastable_recovery_kill_test_bites_on_stuck_goodput():
    """The seeded kill-test: a history whose tail stays collapsed after
    the faults cleared MUST trip the checker -- proof the invariant is
    live, not vacuous."""
    history = (
        [_op(t) for t in range(0, 200, 10)]
        + [_op(t, PutAck.STATUS_RETRY) for t in range(3000, 3200, 10)]
    )
    with pytest.raises(InvariantViolation) as err:
        check_metastable_recovery(
            history, faulted_from_ms=1000, healed_at_ms=3000
        )
    assert err.value.invariant == "metastable-recovery"
    assert "did not recover" in str(err.value)


def test_metastable_recovery_vacuous_cases():
    # too few ops in either segment: no claim
    thin = [_op(0)] + [_op(3000, PutAck.STATUS_RETRY)]
    check_metastable_recovery(thin, faulted_from_ms=1000, healed_at_ms=2000)
    # baseline already degraded below the floor: judges recovery only
    degraded = (
        [_op(t, PutAck.STATUS_RETRY) for t in range(0, 200, 10)]
        + [_op(t, PutAck.STATUS_RETRY) for t in range(3000, 3200, 10)]
    )
    check_metastable_recovery(
        degraded, faulted_from_ms=1000, healed_at_ms=3000
    )
    # NOT_FOUND counts as good for reads, bad for writes
    reads = [_op(t, PutAck.STATUS_NOT_FOUND, op="get")
             for t in range(0, 200, 10)]
    writes = [_op(t, PutAck.STATUS_NOT_FOUND) for t in range(3000, 3200, 10)]
    with pytest.raises(InvariantViolation):
        check_metastable_recovery(
            reads + writes, faulted_from_ms=1000, healed_at_ms=3000
        )


def test_goodput_samples_folds_history_on_grid():
    history = [
        _op(0), _op(100), _op(300, PutAck.STATUS_RETRY),
        _op(300, PutAck.STATUS_NOT_FOUND, op="get"),
    ]
    assert goodput_samples(history, bucket_ms=256) == [
        (0, 2, 2), (256, 2, 1),
    ]


# ---------------------------------------------------------------------------
# the wire and the service
# ---------------------------------------------------------------------------

SLO_STATUS = ClusterStatusResponse(
    sender=Endpoint.from_parts("10.0.0.1", 4000),
    configuration_id=11, membership_size=3,
    slo_names=("serving.latency:fast",),
    slo_burn_milli=(42_100,),
    slo_firing=(1,),
    slo_attributed_trace=(7,),
)


def test_slo_digest_round_trips_both_transports():
    assert decode(encode(9, SLO_STATUS)) == (9, SLO_STATUS)
    wire = gt.to_wire_response(SLO_STATUS).SerializeToString(
        deterministic=True
    )
    back = gt.from_wire_response(MSG["RapidResponse"].FromString(wire))
    assert back == SLO_STATUS
    # a pre-SLO frame decodes to the empty defaults on both transports
    old = ClusterStatusResponse(
        sender=SLO_STATUS.sender, configuration_id=11, membership_size=3,
    )
    assert decode(encode(9, old))[1].slo_names == ()


def _status(h, probe, target):
    p = probe.send_message(target, ClusterStatusRequest(
        sender=probe.address,
    ))
    assert h.scheduler.run_until(p.done, timeout_ms=60_000)
    assert p.exception() is None, p.exception()
    return p.peek()


def _serving_cluster(seed, settings):
    h = ClusterHarness(seed=seed, settings=settings)
    placement = {"partitions": 16, "replicas": 3, "seed": 7}
    h.start_seed(0, placement=placement, serving=True,
                 handoff=InMemoryPartitionStore)
    for i in range(1, 3):
        h.join(i, placement=placement, serving=True,
               handoff=InMemoryPartitionStore)
    h.wait_and_verify_agreement(3)
    return h


def test_cluster_status_carries_slo_digest_when_enabled():
    settings = Settings(slo=SLOSettings(enabled=True, window_scale=0.001))
    h = _serving_cluster(21, settings)
    try:
        cluster = h.instances[h.addr(0)]
        for j in range(12):
            promise = cluster.serving_put(b"k%d" % j, b"v%d" % j)
            assert h.scheduler.run_until(promise.done, timeout_ms=60_000)
            assert promise.peek().status == PutAck.STATUS_OK
        probe = InProcessClient(
            Endpoint.from_parts("127.0.0.1", 9997), h.network, h.settings
        )
        reply = _status(h, probe, h.addr(0))
        assert set(reply.slo_names) == {
            f"{slo}:{w}" for slo in SLO_CATALOG for w in ("fast", "slow")
        }
        assert len(reply.slo_burn_milli) == len(reply.slo_names)
        assert len(reply.slo_firing) == len(reply.slo_names)
        assert len(reply.slo_attributed_trace) == len(reply.slo_names)
        # healthy serving traffic: nothing fires, burns stay at zero
        assert set(reply.slo_firing) == {0}
        assert all(b == 0 for b in reply.slo_burn_milli)
    finally:
        h.shutdown()


def test_cluster_status_slo_kill_switch_off_is_pre_slo_surface():
    h = _serving_cluster(22, Settings())  # default: slo disabled
    try:
        cluster = h.instances[h.addr(0)]
        promise = cluster.serving_put(b"k", b"v")
        assert h.scheduler.run_until(promise.done, timeout_ms=60_000)
        probe = InProcessClient(
            Endpoint.from_parts("127.0.0.1", 9996), h.network, h.settings
        )
        reply = _status(h, probe, h.addr(0))
        assert reply.slo_names == ()
        assert reply.slo_burn_milli == ()
        assert reply.slo_firing == ()
        assert reply.slo_attributed_trace == ()
    finally:
        h.shutdown()


# ---------------------------------------------------------------------------
# catalogs and tools
# ---------------------------------------------------------------------------


def test_catalogs_are_consistent():
    for name, spec in SLO_CATALOG.items():
        assert spec["sli"] in SLI_CATALOG, name
        assert 0.0 < spec["objective"] < 1.0, name
        assert spec["windows"], name
        for w in spec["windows"]:
            assert w in BURN_WINDOWS, name
        if spec["sli"] == "fast-availability":
            assert spec["latency_threshold_ms"] > 0, name
    for pair in BURN_WINDOWS.values():
        assert 0 < pair["short_s"] < pair["long_s"]
        assert pair["burn"] > 0


def test_tools_slo_renders_burning_and_ok_lines():
    from tools.slo import render_slo, to_json

    journal = tuple(json.dumps(e) for e in [
        {"kind": "fd_signal", "virtual_ms": 50, "detail": {"trace_id": 7}},
        {"kind": "view_install", "virtual_ms": 120, "node": "n0",
         "detail": {"trace_id": 7, "removed": 3, "configuration_id": 2}},
        {"kind": "placement_rebalance", "virtual_ms": 110,
         "detail": {"configuration_id": 2, "moved": 41}},
    ])
    status = ClusterStatusResponse(
        sender=SLO_STATUS.sender, configuration_id=11, membership_size=3,
        journal=journal,
        slo_names=("serving.latency:fast", "serving.availability:fast"),
        slo_burn_milli=(42_100, 150),
        slo_firing=(1, 0),
        slo_attributed_trace=(7, 0),
    )
    text = render_slo(status)
    lines = text.splitlines()
    # firing alerts sort first and carry the full attribution sentence
    assert "SLO burning: p99 latency (serving.latency:fast, burn 42.1x)" \
        in lines[1]
    assert "view-change episode 7 (3 nodes evicted, 41 partitions moved)" \
        in lines[1]
    assert "SLO ok: availability" in lines[2]

    doc = to_json(status)
    assert doc["firing"] == 1
    assert doc["alerts"]["serving.latency:fast"]["attributed_trace"] == 7

    # a kill-switch-off node renders the explicit off notice
    off = ClusterStatusResponse(
        sender=SLO_STATUS.sender, configuration_id=11, membership_size=3,
    )
    assert "settings.slo.enabled is off" in render_slo(off)
