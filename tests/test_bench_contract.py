"""bench.py's driver contract (VERDICT r3 items 1 & 6): the per-round
artifact must distinguish infrastructure outage (rc 17) from perf
regression (rc 18) from success (rc 0), survive a partial sweep failure,
and carry the full scaling curve in the one JSON line.

The measurement math itself is guarded by test_bench_regression.py; these
tests cover the orchestration with the device layer stubbed out, so they
run in the plain CPU battery with no tunnel dependency.
"""

import json

import pytest

import bench


class _FakeRecord:
    virtual_time_ms = 11_100
    configuration_id = -42
    membership_size = bench.N_NODES - 1000

    cut = list(range(1000))


def _fake_warmed_run(wall_ms):
    def run(n_nodes, seed, fail_fraction=bench.FAIL_FRACTION,
            placement_partitions=0, handoff_partitions=0):
        return wall_ms, _FakeRecord(), 1.0, 2.0

    return run


def test_probe_gives_up_after_bounded_retries(monkeypatch):
    attempts = []
    monkeypatch.setattr(
        bench, "_probe_backend_once", lambda t: attempts.append(t) or None
    )
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench.probe_backend() is None
    assert tuple(attempts) == bench.PROBE_TIMEOUTS_S  # bounded, not forever


def test_probe_returns_first_success(monkeypatch):
    calls = [None, "tpu"]
    monkeypatch.setattr(bench, "_probe_backend_once", lambda t: calls.pop(0))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    assert bench.probe_backend() == "tpu"


def test_unreachable_accelerator_exits_17(monkeypatch, capsys):
    monkeypatch.setattr(bench, "_arm_watchdog", lambda: None)
    monkeypatch.setattr(bench, "probe_backend", lambda: None)
    with pytest.raises(SystemExit) as exc:
        bench.main()
    assert exc.value.code == 17
    # no measurement, but still one well-formed artifact line: the harness
    # reads outage=true instead of inferring the outage from empty stdout
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert parsed["outage"] is True
    assert parsed["value"] is None
    assert "unreachable" in parsed["reason"]
    assert "time_to_stable_view_ms" in parsed  # sim-plane telemetry carried
    # outage lines carry device attribution too (None/0 when jax is down)
    assert "device_kind" in parsed and "mesh_shape" in parsed


def test_budget_breach_prints_json_then_exits_18(monkeypatch, capsys):
    monkeypatch.setattr(bench, "_arm_watchdog", lambda: None)
    monkeypatch.setattr(bench, "probe_backend", lambda: "tpu")
    monkeypatch.setattr(bench, "warmed_run", _fake_warmed_run(bench.TPU_BUDGET_MS + 50))
    monkeypatch.setattr(bench, "run_sweep", lambda backend, seed: [])
    with pytest.raises(SystemExit) as exc:
        bench.main()
    assert exc.value.code == 18
    # the measurement is still the artifact: JSON printed before the rc
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert parsed["value"] == bench.TPU_BUDGET_MS + 50
    assert parsed["backend"] == "tpu"


def test_success_emits_sweep_curve(monkeypatch, capsys):
    monkeypatch.setattr(bench, "_arm_watchdog", lambda: None)
    monkeypatch.setattr(bench, "probe_backend", lambda: "tpu")
    monkeypatch.setattr(bench, "warmed_run", _fake_warmed_run(120.0))
    monkeypatch.setattr(
        bench,
        "run_sweep",
        lambda backend, seed: [
            {"n": 1_000, "warmed_wall_ms": 30.0, "virtual_ms": 11_100, "cut_ok": True},
            {"n": 1_000_000, "warmed_wall_ms": 470.0, "virtual_ms": 11_100, "cut_ok": True},
        ],
    )
    bench.main()  # rc 0: returns normally
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert parsed["value"] == 120.0
    assert parsed["vs_baseline"] == round(120.0 / bench.BASELINE_MS, 4)
    sizes = [e["n"] for e in parsed["sweep"]]
    assert sizes == [1_000, 100_000, 1_000_000]  # headline folded in, sorted
    # every artifact line names the hardware that produced it: device kind
    # plus the mesh/device topology the sim plane would shard over
    assert isinstance(parsed["device_kind"], str) and parsed["device_kind"]
    assert parsed["device_count"] >= 1
    assert parsed["process_count"] >= 1
    assert parsed["mesh_shape"] == {"nodes": parsed["device_count"]}


def test_cpu_wall_within_budget_is_rc0(monkeypatch, capsys):
    """A CPU run never trips the TPU budget (the driver's TPU-side guard
    must not misfire when the bench is exercised off-hardware)."""
    monkeypatch.setattr(bench, "_arm_watchdog", lambda: None)
    monkeypatch.setattr(bench, "probe_backend", lambda: "cpu")
    monkeypatch.setattr(bench, "warmed_run", _fake_warmed_run(3000.0))
    monkeypatch.setattr(bench, "run_sweep", lambda backend, seed: [])
    bench.main()
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert parsed["backend"] == "cpu"


def test_watchdog_emits_partial_artifact_after_headline(monkeypatch, capsys):
    """A hang AFTER the headline measurement (e.g. the 1M sweep point
    against a dying tunnel) must not destroy it: the watchdog emits the
    JSON with the completed sweep entries plus an error marker, rc 0."""
    monkeypatch.setitem(bench._PROGRESS, "headline",
                        {"value": 120.0, "virtual_ms": 11_100})
    monkeypatch.setitem(bench._PROGRESS, "backend", "tpu")
    monkeypatch.setitem(
        bench._PROGRESS, "sweep",
        [{"n": 1_000, "warmed_wall_ms": 30.0, "virtual_ms": 11_100,
          "cut_ok": True}],
    )
    assert bench._on_watchdog() == 0
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert parsed["value"] == 120.0
    sizes = [e.get("n") for e in parsed["sweep"]]
    assert sizes[:2] == [1_000, bench.N_NODES]  # error marker sorts last
    assert "watchdog" in parsed["sweep"][-1]["error"]


def test_watchdog_without_headline_is_rc17(monkeypatch, capsys):
    monkeypatch.setitem(bench._PROGRESS, "headline", None)
    assert bench._on_watchdog() == 17
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert parsed["outage"] is True
    assert parsed["value"] is None
    assert "watchdog" in parsed["reason"]


def test_sweep_parity_failure_crashes_the_bench(monkeypatch):
    """A cut-parity AssertionError at a sweep size is a correctness bug:
    it must propagate (generic nonzero rc), never become an rc-0 error
    entry."""
    def bad_parity(n_nodes, seed, fail_fraction=bench.FAIL_FRACTION,
                   placement_partitions=0, handoff_partitions=0):
        raise AssertionError("cut-set parity violated")

    monkeypatch.setattr(bench, "warmed_run", bad_parity)
    monkeypatch.setitem(bench._PROGRESS, "sweep", [])
    with pytest.raises(AssertionError):
        bench.run_sweep("tpu", seed=42)


def test_sweep_isolates_per_size_failures(monkeypatch):
    def flaky(n_nodes, seed, fail_fraction=bench.FAIL_FRACTION,
              placement_partitions=0, handoff_partitions=0):
        if n_nodes == 10_000:
            raise RuntimeError("boom")
        return 50.0, _FakeRecord(), 1.0, 2.0

    monkeypatch.setattr(bench, "warmed_run", flaky)
    sweep = bench.run_sweep("tpu", seed=42)
    by_n = {e["n"]: e for e in sweep}
    assert by_n[1_000]["warmed_wall_ms"] == 50.0
    assert "boom" in by_n[10_000]["error"]
    assert by_n[1_000_000]["warmed_wall_ms"] == 50.0  # later sizes still ran


def test_telemetry_overhead_within_budget():
    """Instrumenting the sim loop must cost (close to) nothing: the warmed
    decision loop with the real registry stays within 5% of an identical run
    on NullMetrics, plus a small absolute allowance for timer noise (the
    telemetry delta on a ~10ms loop is far below scheduler jitter)."""
    import time

    import numpy as np

    from rapid_tpu.observability import Metrics, NullMetrics
    from rapid_tpu.sim.driver import Simulator

    def best_of(metrics_factory, runs=5):
        best = float("inf")
        for _ in range(runs):
            sim = Simulator(64, seed=5, metrics=metrics_factory())
            sim.ready()
            sim.crash(np.array([3]))
            t0 = time.perf_counter()
            record = sim.run_until_decision(max_rounds=40)
            best = min(best, time.perf_counter() - t0)
            assert record is not None
        return best

    best_of(NullMetrics, runs=1)  # jit warmup, shapes shared by both sides
    noop = best_of(NullMetrics)
    instrumented = best_of(Metrics)  # detached registry: same record path
    assert instrumented <= noop * 1.05 + 0.05, (
        f"telemetry overhead: instrumented={instrumented * 1e3:.1f}ms "
        f"noop={noop * 1e3:.1f}ms"
    )


def test_lockdep_overhead_within_budget(monkeypatch):
    """RAPID_LOCKDEP=1 is on for the whole tier-1 battery (conftest), so the
    instrumentation must be cheap enough to leave the bench contract intact:
    the warmed decision loop with instrumented locks stays within the same
    envelope as plain threading locks, and the wrapper's per-acquire cost is
    bounded in absolute terms.

    enabled() is sampled at make_lock() time, so toggling the env var around
    construction is what flips a scenario between plain and instrumented.
    """
    import sys
    import time

    import numpy as np

    from rapid_tpu.observability import Metrics
    from rapid_tpu.runtime import lockdep
    from rapid_tpu.sim.driver import Simulator

    # tools/coverage.py's settrace collector pays a call event on every
    # wrapper frame the plain C lock never makes; timing bounds are
    # meaningless under it
    traced = sys.gettrace() is not None

    # -- micro: the wrapper itself ----------------------------------------
    def per_op(lock, ops=20_000, runs=3):
        best = float("inf")
        for _ in range(runs):
            t0 = time.perf_counter()
            for _ in range(ops):
                with lock:
                    pass
            best = min(best, time.perf_counter() - t0)
        return best / ops

    monkeypatch.setenv("RAPID_LOCKDEP", "0")
    plain_op = per_op(lockdep.make_lock("bench.plain"))
    monkeypatch.setenv("RAPID_LOCKDEP", "1")
    inst_op = per_op(lockdep.make_lock("bench.instrumented"))
    # TLS stack walk + one graph-lock hop: an order of magnitude over a raw
    # lock is expected; tens of microseconds per op is not
    budget = 200e-6 if traced else 20e-6
    assert inst_op < budget, f"instrumented acquire: {inst_op * 1e6:.1f}us/op"
    assert inst_op < plain_op * 200 + budget

    # -- macro: the warmed decision loop, locks created under each mode ----
    def best_of(runs=5):
        best = float("inf")
        for _ in range(runs):
            sim = Simulator(64, seed=5, metrics=Metrics())
            sim.ready()
            sim.crash(np.array([3]))
            t0 = time.perf_counter()
            record = sim.run_until_decision(max_rounds=40)
            best = min(best, time.perf_counter() - t0)
            assert record is not None
        return best

    best_of(runs=1)  # jit warmup, shapes shared by both sides
    monkeypatch.setenv("RAPID_LOCKDEP", "0")
    plain = best_of()
    monkeypatch.setenv("RAPID_LOCKDEP", "1")
    instrumented = best_of()
    slack = 0.25 if traced else 0.05
    assert instrumented <= plain * 1.10 + slack, (
        f"lockdep overhead: instrumented={instrumented * 1e3:.1f}ms "
        f"plain={plain * 1e3:.1f}ms"
    )


def test_bench_headline_steady_state_compiles_zero():
    """The real warmed_run (small n) must report zero steady-state
    recompilations: all compilation belongs to the warmup phase, and the
    timed window (armed inside warmed_run) would have raised on any compile
    or implicit transfer in the measured region."""
    wall_ms, record, build_s, warm_wall = bench.warmed_run(256, seed=9)
    stats = dict(bench._LAST_JIT_STATS)
    assert stats["jit_compiles_steady"] == 0, stats
    assert stats["jit_compile_ms_steady"] == 0.0, stats
    # warmup compiles are >= 0 (0 when an earlier in-process test already
    # populated jax's caches for these shapes) and the wall-time field is
    # consistent with the count
    assert stats["jit_compiles_warmup"] >= 0
    if stats["jit_compiles_warmup"] == 0:
        assert stats["jit_compile_ms_warmup"] == 0.0


def test_bench_sweep_entries_carry_jit_stats(monkeypatch):
    """The per-sweep-point JSON entries include the compile telemetry
    captured by the last warmed_run."""
    def fake(n_nodes, seed, fail_fraction=bench.FAIL_FRACTION,
             placement_partitions=0, handoff_partitions=0):
        bench._LAST_JIT_STATS.clear()
        bench._LAST_JIT_STATS.update({
            "jit_compiles_warmup": 7, "jit_compile_ms_warmup": 123.0,
            "jit_compiles_steady": 0, "jit_compile_ms_steady": 0.0,
        })
        return 50.0, _FakeRecord(), 1.0, 2.0

    monkeypatch.setattr(bench, "warmed_run", fake)
    sweep = bench.run_sweep("tpu", seed=42)
    for entry in sweep:
        assert entry["jit_compiles_warmup"] == 7
        assert entry["jit_compiles_steady"] == 0


def test_serving_dimension_json_contract(monkeypatch, capsys):
    """The serving_qps entry of the one JSON line carries, for every
    measurement window (steady / view_change_window / post_view), the p99
    and the full latency histogram on the declared bucket ladder -- the
    harness plots the view-change latency spike straight from the
    artifact. Run at a reduced scale so the contract check stays cheap."""
    from rapid_tpu.observability import SERVING_LATENCY_BUCKETS_MS

    monkeypatch.setattr(bench, "SERVING_N_NODES", 16)
    monkeypatch.setattr(bench, "SERVING_PARTITIONS", 32)
    monkeypatch.setattr(bench, "SERVING_KEYS", 12)
    monkeypatch.setattr(
        bench, "SERVING_OPS",
        {"steady": 40, "view_change_window": 20, "post_view": 20},
    )
    entry = bench.run_serving_dimension(seed=3)
    assert entry["lost_acked_writes"] == 0
    assert entry["throughput_qps"] > 0
    ladder = [str(b) for b in SERVING_LATENCY_BUCKETS_MS] + ["inf"]
    for window, ops in (("steady", 40), ("view_change_window", 20),
                        ("post_view", 20)):
        stats = entry[window]
        assert stats["count"] == ops
        assert stats["p99_ms"] is not None and stats["p99_ms"] >= stats["p50_ms"]
        hist = stats["latency_hist_ms"]
        assert list(hist) == ladder
        counts = list(hist.values())
        assert counts == sorted(counts)  # cumulative buckets
        assert hist["inf"] == ops
    # the SLO plane rode the same open-loop stream: its summary is part of
    # the artifact (availability, p99, goodput, per-window burn peaks)
    assert entry["offered_rate_per_s"] == bench.SERVING_RATE_PER_S
    slo = entry["slo"]
    assert set(slo) == {"serving.availability", "serving.latency"}
    for name, summary in slo.items():
        assert 0.0 <= summary["availability"] <= 1.0
        assert 0.0 <= summary["goodput_ratio"] <= 1.0
        assert summary["peak_burn"] >= 0.0
        assert set(summary["alerts"]) == {"fast", "slow"}
        for alert in summary["alerts"].values():
            assert alert["burn_short"] >= 0.0
            assert alert["burn_long"] >= 0.0
    # and the emitter folds the entry into the artifact line verbatim
    bench._emit_json(
        {"value": 120.0, "virtual_ms": 11_100}, "cpu", []
    )
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert parsed["serving_qps"] == entry


def test_open_loop_generator_deterministic():
    """The open-loop arrival stream is a pure function of its seed: same
    seed -> identical schedule (timestamps, ops, keys, clients), different
    seed -> a different one. The serving dimension's determinism per seed
    rests on this."""
    from rapid_tpu.slo import OpenLoopGenerator

    keys = [b"k-%02d" % i for i in range(8)]

    def stream(seed):
        gen = OpenLoopGenerator(500.0, keys, put_fraction=0.3, seed=seed)
        return [(a.at_ms, a.op, a.key, a.client) for a in gen.arrivals(200)]

    first = stream(7)
    assert first == stream(7)
    assert first != stream(8)
    # open loop: arrival times are monotone and rate-scheduled, never
    # gated on completions (no completion signal even exists here)
    times = [t for t, _op, _k, _c in first]
    assert times == sorted(times)
    assert any(op == "put" for _t, op, _k, _c in first)
    assert any(op == "get" for _t, op, _k, _c in first)
    # zipfian keys: the hottest key strictly dominates the coldest
    from collections import Counter

    freq = Counter(k for _t, _op, k, _c in first)
    assert freq[keys[0]] > freq.get(keys[-1], 0)


def _reduced_messaging_scale(monkeypatch):
    monkeypatch.setattr(bench, "MESSAGING_PAIR_MSGS", 64)
    monkeypatch.setattr(bench, "MESSAGING_STORM_NODES", 4)
    monkeypatch.setattr(bench, "MESSAGING_STORM_ROUNDS", 5)
    monkeypatch.setattr(bench, "MESSAGING_STORM_BURST", 4)


def test_messaging_dimension_json_contract(monkeypatch, capsys):
    """The messaging_throughput entry of the one JSON line carries the
    loopback RPC rate, the broadcast-storm curve on the event-loop core,
    the thread-per-message baseline, and the two A/B headline ratios the
    harness tracks (messages/sec speedup and write-syscall reduction).
    Run at a reduced scale so the contract check stays cheap."""
    _reduced_messaging_scale(monkeypatch)
    entry = bench.run_messaging_dimension(seed=3)
    for workload in ("loopback_pair", "broadcast_storm", "threaded_baseline"):
        stats = entry[workload]
        assert stats["messages"] > 0
        assert stats["messages_per_s"] > 0
        assert stats["bytes_per_s"] > 0
        assert "flush_syscalls_per_msg" in stats
    storm = entry["broadcast_storm"]
    assert storm["messages"] == 4 * 3 * 5 * 4  # n*(n-1)*rounds*burst, exact
    assert storm["frames_sent"] > 0
    assert entry["speedup_vs_threaded"] > 0
    assert entry["syscall_reduction_vs_threaded"] > 0
    # and the emitter folds the entry into the artifact line verbatim
    bench._emit_json({"value": 120.0, "virtual_ms": 11_100}, "cpu", [])
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert parsed["messaging_throughput"] == entry


def test_gray_detection_dimension_json_contract(monkeypatch, capsys):
    """The gray_detection_ms entry of the one JSON line carries, for both
    gray fault shapes (a node that stays gray, a node flapping slow/healthy
    across windows), the static and adaptive detection->decision latencies
    and their ratio, with the >= 2x adaptive speedup the dimension itself
    asserts. Run at a reduced scale so the contract check stays cheap."""
    monkeypatch.setattr(bench, "GRAY_N_NODES", 16)
    entry = bench.run_gray_detection_dimension(seed=3)
    assert entry["n"] == 16
    for scenario in ("gray_slow_node", "gray_flapping"):
        stats = entry[scenario]
        assert stats["static_ms"] > stats["adaptive_ms"] > 0
        assert stats["speedup"] >= 2.0
    # flapping punishes the static counter extra: it must straddle a healthy
    # gap the adaptive streak never sees
    assert (
        entry["gray_flapping"]["static_ms"]
        > entry["gray_slow_node"]["static_ms"]
    )
    # and the emitter folds the entry into the artifact line verbatim
    bench._emit_json({"value": 120.0, "virtual_ms": 11_100}, "cpu", [])
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert parsed["gray_detection_ms"] == entry


def test_recovery_dimension_json_contract(monkeypatch, capsys):
    """The recovery_time_ms entry of the one JSON line carries, for every
    (log length, snapshot cadence) grid point, the exact replayed-record
    count and the cold-start replay wall time -- the harness plots the
    log-over-snapshot recovery curve straight from the artifact. Run at a
    reduced scale so the contract check stays cheap."""
    monkeypatch.setattr(bench, "RECOVERY_LOG_RECORDS", (32, 96))
    monkeypatch.setattr(bench, "RECOVERY_SNAPSHOT_EVERY", (0, 32))
    monkeypatch.setattr(bench, "RECOVERY_VALUE_BYTES", 64)
    entry = bench.run_recovery_dimension(seed=3)
    assert entry["partitions"] == bench.RECOVERY_PARTITIONS
    by_grid = {
        (p["log_records"], p["snapshot_every"]): p for p in entry["points"]
    }
    assert set(by_grid) == {(32, 0), (96, 0), (32, 32), (96, 32)}
    for (records, every), point in by_grid.items():
        # replay is exact and deterministic: records since the last
        # auto-checkpoint (the dimension itself asserts content parity)
        assert point["replayed_records"] == (records % every if every else records)
        assert point["segments"] >= 1
        assert point["recovery_ms"] >= 0
    assert by_grid[(96, 0)]["replayed_records"] == 96   # full-log replay
    assert by_grid[(32, 32)]["replayed_records"] == 0   # snapshot absorbed it
    # and the emitter folds the entry into the artifact line verbatim
    bench._emit_json({"value": 120.0, "virtual_ms": 11_100}, "cpu", [])
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[0])
    assert parsed["recovery_time_ms"] == entry


def test_durability_kill_switch_default_off_keeps_memory_path(tmp_path):
    """DurabilitySettings defaults enabled=False (the kill switch): a
    builder handed a durability directory must not mount the WAL store or
    write a single byte under it -- the node runs the exact pre-durability
    in-memory path, so the switch carries zero overhead when off. Flipping
    it on mounts (and recovers) the store in the same directory."""
    from rapid_tpu.cluster import ClusterBuilder
    from rapid_tpu.settings import DurabilitySettings, Settings
    from rapid_tpu.types import Endpoint

    directory = tmp_path / "wal"
    directory.mkdir()
    builder = ClusterBuilder(Endpoint.from_parts("127.0.0.1", 1234))
    builder.use_durability(str(directory))
    assert builder._durable_store() is None       # switch off: no plane
    assert list(directory.iterdir()) == []        # and no WAL side effects

    builder.use_settings(Settings(durability=DurabilitySettings(enabled=True)))
    store = builder._durable_store()
    assert store is not None
    assert any(directory.iterdir())               # recovery mounted the WAL
    assert builder._handoff_store is store        # downstream planes ride it
    store.close()


def test_messaging_reactor_coalesces_vs_threaded_baseline(monkeypatch):
    """The A/B the refactor exists for, guarded at reduced scale: the
    threaded baseline pays exactly one write syscall per message by
    construction, while the reactor+batching storm coalesces the same
    traffic into far fewer writes. Syscall counts are deterministic-ish
    (timing only shifts HOW MANY messages share a flush, and a whole
    burst fits one window at this scale), so the guard binds them hard;
    wall-clock speedup is asserted only to exist and be positive --
    magnitude claims belong to the full-scale bench artifact, not a
    shared CI box."""
    _reduced_messaging_scale(monkeypatch)
    storm = bench._messaging_reactor_storm()
    baseline = bench._messaging_threaded_baseline()
    assert baseline["flush_syscalls_per_msg"] == 1.0
    assert storm["flush_syscalls_per_msg"] <= 0.5
    assert (
        baseline["flush_syscalls_per_msg"] / storm["flush_syscalls_per_msg"]
        >= 2.0
    )
    assert storm["messages_per_s"] > 0 and baseline["messages_per_s"] > 0


def test_serving_sim_steady_state_compiles_zero(monkeypatch):
    """With the serving plane enabled, a warmed crash->decision loop plus
    client traffic must not compile anything new: serving ops are host-side
    bookkeeping over the handoff stores and must not perturb the device
    program (no new jit cache keys in steady state)."""
    import numpy as np

    from rapid_tpu.runtime import jitwatch
    from rapid_tpu.sim.driver import Simulator

    monkeypatch.setenv("RAPID_JITWATCH", "1")

    def run():
        sim = Simulator(64, seed=5)
        sim.ready()
        sim.enable_placement(partitions=64)
        sim.enable_handoff()
        sim.enable_serving()
        for i in range(8):
            ack = sim.serving_put(b"jw-%02d" % i, b"x")
            assert ack.status == ack.STATUS_OK
        sim.crash(np.array([3]))
        record = sim.run_until_decision(max_rounds=40)
        assert record is not None
        for i in range(8):
            sim.serving_get(b"jw-%02d" % i)

    run()  # warmup: every compile belongs here
    before = jitwatch.compile_count()
    run()
    assert jitwatch.compile_count() == before, (
        f"serving steady state recompiled: "
        f"{jitwatch.compile_events()[before:]}"
    )


def test_serving_overhead_within_budget():
    """enable_serving must not tax the membership protocol itself: the
    warmed crash->decision loop with the serving plane attached (stores
    preloaded, reconcile + cache invalidation running at the view change)
    stays within the same envelope as placement+handoff alone."""
    import sys
    import time

    import numpy as np

    from rapid_tpu.sim.driver import Simulator

    traced = sys.gettrace() is not None

    def best_of(serving, runs=5):
        best = float("inf")
        for _ in range(runs):
            sim = Simulator(64, seed=5)
            sim.ready()
            sim.enable_placement(partitions=64)
            sim.enable_handoff()
            if serving:
                sim.enable_serving()
                for i in range(16):
                    sim.serving_put(b"ovh-%02d" % i, b"x")
            sim.crash(np.array([3]))
            t0 = time.perf_counter()
            record = sim.run_until_decision(max_rounds=40)
            best = min(best, time.perf_counter() - t0)
            assert record is not None
        return best

    best_of(True, runs=1)  # jit warmup, shapes shared by both sides
    plain = best_of(False)
    with_serving = best_of(True)
    slack = 0.25 if traced else 0.05
    assert with_serving <= plain * 1.10 + slack, (
        f"serving overhead: with={with_serving * 1e3:.1f}ms "
        f"without={plain * 1e3:.1f}ms"
    )


def test_jitwatch_overhead_within_budget(monkeypatch):
    """RAPID_JITWATCH=1 is on for the whole tier-1 battery (conftest), so the
    make_jit wrapper must be cheap enough to leave the bench contract intact:
    a warm watched dispatch stays within microseconds of the raw jitted call,
    and the warmed decision loop with recording on stays within the same
    envelope as with recording off.

    enabled() picks raw-vs-wrapped at make_jit() time but is re-checked per
    call, so toggling the env var around the *calls* is what flips a wrapper
    between recording and pass-through (the A/B this test needs).
    """
    import sys
    import time

    import jax.numpy as jnp
    import numpy as np

    from rapid_tpu.observability import Metrics
    from rapid_tpu.runtime import jitwatch
    from rapid_tpu.sim.driver import Simulator

    # tools/coverage.py's settrace collector pays a call event on every
    # wrapper frame the raw jit call never makes; timing bounds are
    # meaningless under it
    traced = sys.gettrace() is not None

    # -- micro: the wrapper itself ----------------------------------------
    def per_op(fn, x, ops=2_000, runs=3):
        best = float("inf")
        for _ in range(runs):
            t0 = time.perf_counter()
            for _ in range(ops):
                fn(x)
            best = min(best, time.perf_counter() - t0)
        return best / ops

    import jax

    x = jnp.zeros((8,), jnp.int32)
    raw = jax.jit(lambda v: v + 1)
    watched = jitwatch.make_jit("bench.jw_micro", lambda v: v + 1)
    assert isinstance(watched, jitwatch._WatchedJit)
    raw(x), watched(x)  # warm both
    raw_op = per_op(raw, x)
    inst_op = per_op(watched, x)
    # env read + two clock reads + a cache-size probe on top of dispatch
    budget = 200e-6 if traced else 20e-6
    assert inst_op - raw_op < budget, (
        f"jitwatch wrapper: {inst_op * 1e6:.1f}us/op vs raw "
        f"{raw_op * 1e6:.1f}us/op"
    )

    # -- macro: the warmed decision loop, recording off vs on --------------
    def best_of(runs=5):
        best = float("inf")
        for _ in range(runs):
            sim = Simulator(64, seed=5, metrics=Metrics())
            sim.ready()
            sim.crash(np.array([3]))
            t0 = time.perf_counter()
            record = sim.run_until_decision(max_rounds=40)
            best = min(best, time.perf_counter() - t0)
            assert record is not None
        return best

    best_of(runs=1)  # jit warmup, shapes shared by both sides
    monkeypatch.setenv("RAPID_JITWATCH", "0")
    plain = best_of()
    monkeypatch.setenv("RAPID_JITWATCH", "1")
    instrumented = best_of()
    slack = 0.25 if traced else 0.05
    assert instrumented <= plain * 1.10 + slack, (
        f"jitwatch overhead: instrumented={instrumented * 1e3:.1f}ms "
        f"plain={plain * 1e3:.1f}ms"
    )


def test_perfscope_trend_contract(tmp_path):
    """ISSUE 18 satellite: the trend subcommand renders the headline
    trajectory across the repo's committed BENCH_rNN artifacts -- outage
    runs (rc 17) are marked in place but never plotted as regressions --
    and flags a >threshold slowdown between measured neighbors with rc 3."""
    from pathlib import Path

    from tools.perfscope import load_trend_entry, trend_report

    root = Path(bench.__file__).parent
    entries = [
        load_trend_entry(str(root / f"BENCH_r{i:02d}.json"))
        for i in range(1, 6)
    ]
    text, regressions = trend_report(entries)
    assert "5 runs (2 measured, 3 outage)" in text
    assert text.count("OUTAGE") == 3 and "rc 17" in text
    assert "r02" in text and "% vs r01" in text
    assert regressions == []  # outages between runs are not perf points

    # a synthetic >threshold slowdown between measured runs must flag;
    # the outage wedged between them must not break the comparison chain
    def artifact(n, rc, value):
        return {"n": n, "rc": rc, "tail": "",
                "parsed": {"metric": "decision_latency_ms", "value": value}
                if rc == 0 else None}

    paths = []
    for n, rc, value in ((1, 0, 100.0), (2, 17, None), (3, 0, 150.0)):
        p = tmp_path / f"run{n}.json"
        p.write_text(json.dumps(artifact(n, rc, value)))
        paths.append(str(p))
    text2, regs2 = trend_report([load_trend_entry(p) for p in paths])
    assert len(regs2) == 1 and "r01 -> r03" in regs2[0]
    assert "OUTAGE" in text2
