"""TpuSimMessaging bridge: real protocol-plane nodes against TPU-hosted
virtual peers (the BASELINE.json north-star plugin).

A real node built on the untouched ClusterBuilder/Cluster API joins a swarm
of simulated virtual nodes through the standard two-phase protocol, observes
simulated crash cuts through its own FastPaxos, leaves gracefully, and is
itself detected and removed by the simulated failure detectors when it dies.
"""

import random

import numpy as np

from rapid_tpu import ClusterBuilder, Endpoint, Settings
from rapid_tpu.events import ClusterEvents
from rapid_tpu.messaging.inprocess import (
    InProcessClient,
    InProcessNetwork,
    InProcessServer,
)
from rapid_tpu.runtime.scheduler import VirtualScheduler
from rapid_tpu.sim.bridge import TpuSimMessaging


class BridgeHarness:
    def __init__(self, n_virtual: int = 24, capacity: int = 32, seed: int = 5):
        self.scheduler = VirtualScheduler()
        self.network = InProcessNetwork(self.scheduler)
        self.swarm = TpuSimMessaging(
            self.network, n_virtual=n_virtual, capacity=capacity, seed=seed
        )
        self.settings = Settings()
        self.rng = random.Random(seed)

    def join_real_node(self, name: str, port: int = 9000, metadata=None):
        ep = Endpoint.from_parts(name, port)
        server = InProcessServer(ep, self.network)
        builder = (
            ClusterBuilder(ep)
            .set_messaging_client_and_server(
                InProcessClient(ep, self.network, self.settings), server
            )
            .use_scheduler(self.scheduler)
            .use_settings(self.settings)
            .use_rng(random.Random(self.rng.getrandbits(64)))
        )
        if metadata:
            builder.set_metadata(metadata)
        promise = builder.join_async(self.swarm.endpoint(0))
        self.scheduler.run_for(50)  # deliver join phases; park at observers
        rec = self.swarm.pump()
        assert rec is not None, "join did not decide"
        assert self.scheduler.run_until(promise.done, timeout_ms=10_000)
        return promise.result(0), rec


def test_real_node_joins_virtual_swarm():
    h = BridgeHarness(n_virtual=24, seed=5)
    cluster, rec = h.join_real_node("real-1")
    assert len(rec.added) == 1 and len(rec.removed) == 0
    assert cluster.get_membership_size() == 25
    assert h.swarm.sim.membership_size == 25
    # bit-exact configuration identity across the bridge
    assert cluster.get_current_configuration_id() == h.swarm.sim.configuration_id()
    assert cluster.listen_address in cluster.get_memberlist()


def test_real_node_observes_simulated_crash_cut():
    h = BridgeHarness(n_virtual=24, seed=6)
    cluster, _ = h.join_real_node("real-1")
    events = []
    cluster.register_subscription(
        ClusterEvents.VIEW_CHANGE, lambda cid, changes: events.append(changes)
    )
    victims = np.array([3, 11, 17])
    h.swarm.sim.crash(victims)
    rec = h.swarm.pump(max_rounds=16)
    assert rec is not None and sorted(rec.cut) == [3, 11, 17]
    # votes are in flight on the virtual network; let the real node tally them
    h.scheduler.run_for(200)
    assert cluster.get_membership_size() == 22
    assert cluster.get_current_configuration_id() == h.swarm.sim.configuration_id()
    assert len(events) == 1 and len(events[0]) == 3
    crashed_eps = {h.swarm.endpoint(int(v)) for v in victims}
    assert {c.endpoint for c in events[0]} == crashed_eps


def test_real_node_leaves_gracefully():
    h = BridgeHarness(n_virtual=16, seed=7)
    cluster, join_rec = h.join_real_node("real-1")
    done = cluster.leave_gracefully_async()
    h.scheduler.run_for(50)  # LeaveMessages reach the virtual observers
    rec = h.swarm.pump(max_rounds=8)
    assert rec is not None
    assert [h.swarm._endpoint(int(s)) for s in rec.cut] == [cluster.listen_address]
    # leave decided in 1 alert round + 1 vote round, not the 10-round FD wait
    assert rec.virtual_time_ms - join_rec.virtual_time_ms == 2 * 1000 + 100
    assert h.swarm.sim.membership_size == 16
    assert h.scheduler.run_until(done.done, timeout_ms=30_000)


def test_dead_real_node_removed_by_simulated_fd():
    h = BridgeHarness(n_virtual=16, seed=8)
    cluster, _ = h.join_real_node("real-1")
    assert h.swarm.sim.membership_size == 17
    cluster.shutdown()  # server unregisters: the swarm senses the death
    rec = h.swarm.pump(max_rounds=32, batch=16)
    assert rec is not None
    assert [h.swarm._endpoint(int(s)) for s in rec.cut] == [cluster.listen_address]
    assert h.swarm.sim.membership_size == 16


def test_two_real_nodes_share_one_swarm():
    h = BridgeHarness(n_virtual=24, capacity=32, seed=9)
    cluster1, _ = h.join_real_node("real-1", 9000)
    cluster2, _ = h.join_real_node("real-2", 9001)
    # the first node observes the second's admission through votes
    h.scheduler.run_for(200)
    assert cluster1.get_membership_size() == 26
    assert cluster2.listen_address in cluster1.get_memberlist()
    assert (
        cluster1.get_current_configuration_id()
        == cluster2.get_current_configuration_id()
        == h.swarm.sim.configuration_id()
    )
    # a crash cut reaches both real members
    h.swarm.sim.crash(np.array([5]))
    rec = h.swarm.pump(max_rounds=16)
    assert rec is not None
    h.scheduler.run_for(200)
    assert cluster1.get_membership_size() == 25
    assert cluster2.get_membership_size() == 25


def test_join_metadata_travels_through_bridge():
    h = BridgeHarness(n_virtual=16, seed=10)
    cluster, _ = h.join_real_node("real-1", metadata={"zone": b"us-east-1"})
    md = cluster.get_cluster_metadata()
    assert md.get(cluster.listen_address) == (("zone", b"us-east-1"),)


def test_uuid_reuse_rejected_across_bridge():
    h = BridgeHarness(n_virtual=16, seed=11)
    cluster, _ = h.join_real_node("real-1")
    from rapid_tpu.types import JoinStatusCode, NodeId, PreJoinMessage

    # replay a pre-join with an identifier the swarm has already seen
    high, low = (int(x) for x in h.swarm.sim.sorted_identifiers()[0])
    resp = h.swarm._handle_pre_join(
        h.swarm.endpoint(0),
        PreJoinMessage(
            sender=Endpoint.from_parts("real-2", 9002), node_id=NodeId(high, low)
        ),
    )
    assert resp.status_code == JoinStatusCode.UUID_ALREADY_IN_RING


def test_real_node_rejoins_after_leave():
    """A removed real node can rejoin with a fresh UUID: its slot is
    recycled and the identifier history keeps every past identity."""
    h = BridgeHarness(n_virtual=16, seed=13)
    cluster, _ = h.join_real_node("real-1")
    ids_before = len(h.swarm.sim.identifiers_seen)
    done = cluster.leave_gracefully_async()
    h.scheduler.run_for(50)
    assert h.swarm.pump(max_rounds=8) is not None
    assert h.scheduler.run_until(done.done, timeout_ms=30_000)
    assert h.swarm.sim.membership_size == 16

    cluster2, rec = h.join_real_node("real-1")  # same endpoint, fresh UUID
    assert cluster2.get_membership_size() == 17
    assert cluster2.get_current_configuration_id() == h.swarm.sim.configuration_id()
    # both the departed and the rejoined identity are in the history
    assert len(h.swarm.sim.identifiers_seen) == ids_before + 1


def test_rejoin_after_crash_detection():
    """A real node that dies is cut by the simulated FDs and can come back."""
    h = BridgeHarness(n_virtual=16, seed=14)
    cluster, _ = h.join_real_node("real-1")
    cluster.shutdown()
    rec = h.swarm.pump(max_rounds=32, batch=16)
    assert rec is not None and h.swarm.sim.membership_size == 16
    cluster2, _ = h.join_real_node("real-1")
    assert cluster2.get_membership_size() == 17
    assert cluster2.get_current_configuration_id() == h.swarm.sim.configuration_id()


def test_real_node_down_alert_injected_into_swarm():
    """A real observer's DOWN alert about a virtual subject enters the
    simulated report tables."""
    h = BridgeHarness(n_virtual=16, seed=12)
    cluster, _ = h.join_real_node("real-1")
    subjects = cluster._membership_service._view.get_subjects_of(
        cluster.listen_address
    )
    target = subjects[0]
    slot = h.swarm._slot_of[target]
    from rapid_tpu.types import AlertMessage, BatchedAlertMessage, EdgeStatus

    batch = BatchedAlertMessage(
        sender=cluster.listen_address,
        messages=(
            AlertMessage(
                edge_src=cluster.listen_address,
                edge_dst=target,
                edge_status=EdgeStatus.DOWN,
                configuration_id=h.swarm.sim.configuration_id(),
                ring_numbers=(0,),
            ),
        ),
    )
    h.swarm._absorb_alerts(batch)
    assert h.swarm.sim._injected_down[slot, 0]


def test_prejoin_retry_while_join_pending_is_safe():
    """A phase-1 retry (same UUID) while the phase-2 join is parked must
    answer SAFE_TO_JOIN again, not crash on the already-seated identity."""
    h = BridgeHarness(n_virtual=16, seed=15)
    from rapid_tpu.types import JoinMessage, JoinStatusCode, NodeId, PreJoinMessage

    ep = Endpoint.from_parts("real-retry", 9100)
    nid = NodeId.random(random.Random(99))
    seed_ep = h.swarm.endpoint(0)
    first = h.swarm._handle_pre_join(seed_ep, PreJoinMessage(ep, nid))
    assert first.status_code == JoinStatusCode.SAFE_TO_JOIN
    h.swarm._handle_join(
        first.endpoints[0],
        JoinMessage(ep, nid, (0,), first.configuration_id),
    )
    assert h.swarm._slot_of[ep] in h.swarm.sim.pending_joiners
    retry = h.swarm._handle_pre_join(seed_ep, PreJoinMessage(ep, nid))
    assert retry.status_code == JoinStatusCode.SAFE_TO_JOIN
    assert retry.endpoints == first.endpoints


def test_joiner_death_before_admission_reclaims_slot():
    """A joiner that dies between pre-join and admission is withdrawn and its
    spare slot returns to the free list."""
    h = BridgeHarness(n_virtual=16, capacity=20, seed=16)
    free_before = len(h.swarm._free_slots)
    ep = Endpoint.from_parts("doomed", 9200)
    server = InProcessServer(ep, h.network)
    settings = Settings()
    builder = (
        ClusterBuilder(ep)
        .set_messaging_client_and_server(
            InProcessClient(ep, h.network, settings), server
        )
        .use_scheduler(h.scheduler)
        .use_settings(settings)
        .use_rng(random.Random(3))
    )
    builder.join_async(h.swarm.endpoint(0))
    h.scheduler.run_for(50)  # join parked, slot consumed
    assert len(h.swarm._free_slots) == free_before - 1
    assert h.swarm.sim.pending_joiners
    server.shutdown()  # the joiner dies before any decision
    rec = h.swarm.pump(max_rounds=8)
    assert rec is None  # nothing to decide: the join was withdrawn
    assert not h.swarm.sim.pending_joiners
    assert len(h.swarm._free_slots) == free_before
    assert h.swarm.sim.membership_size == 16


def test_quorum_reachable_only_with_real_members_vote():
    """A real member's registered vote completes a fast-round quorum the
    virtual members alone cannot reach: N=16 (15 virtual + 1 real), 3 virtual
    crashed => quorum 13, live virtual voters 12, and the real member's
    FastRoundPhase2bMessage is the 13th vote."""
    h = BridgeHarness(n_virtual=15, capacity=20, seed=9)
    cluster, _ = h.join_real_node("real-1")
    assert h.swarm.sim.membership_size == 16
    victims = np.array([1, 2, 3])
    h.swarm.sim.crash(victims)
    rec = h.swarm.pump(max_rounds=32, classic_fallback_after_rounds=None)
    assert rec is not None, "real member's vote should complete the quorum"
    assert not rec.via_classic_round
    assert sorted(rec.cut) == [1, 2, 3]
    assert h.swarm.sim.membership_size == 13
    # the decision genuinely consumed the real member's registered vote
    assert h.swarm.sim.auto_vote[h.swarm._slot_of[cluster.listen_address]] == False  # noqa: E712


def test_quorum_blocked_when_real_members_vote_is_dropped():
    """Control arm: same scenario, but the real member's vote broadcasts are
    dropped on the wire -- 12 of 16 votes < quorum 13, so the fast round
    stalls until the classic recovery round decides."""
    from rapid_tpu.types import FastRoundPhase2bMessage

    h = BridgeHarness(n_virtual=15, capacity=20, seed=9)
    cluster, _ = h.join_real_node("real-1")
    h.network.add_filter(
        lambda s, d, m: not (
            s == cluster.listen_address and isinstance(m, FastRoundPhase2bMessage)
        )
    )
    h.swarm.sim.crash(np.array([1, 2, 3]))
    rec = h.swarm.pump(max_rounds=32, classic_fallback_after_rounds=None)
    assert rec is None, "12 received votes must not reach the quorum of 13"
    rec = h.swarm.pump(max_rounds=16, classic_fallback_after_rounds=4)
    assert rec is not None and rec.via_classic_round
    assert sorted(rec.cut) == [1, 2, 3]


def test_real_members_conflicting_vote_forces_classic_fallback():
    """A real member that saw different evidence votes a *different* cut; its
    conflicting vote denies the swarm's proposal the 13th vote it needs, and
    the classic recovery round (coordinator value-pick over the actual
    fast-round votes) decides the majority value."""
    from rapid_tpu.types import AlertMessage, BatchedAlertMessage, EdgeStatus

    h = BridgeHarness(n_virtual=15, capacity=20, seed=10)
    cluster, _ = h.join_real_node("real-1")
    victims = np.array([1, 2, 3])
    h.swarm.sim.crash(victims)
    # Asymmetric dissemination: before the swarm's own broadcast, the real
    # member receives evidence for only a PARTIAL cut {1, 2} (K rings each,
    # so its detector crosses H and latches announcedProposal) -- it then
    # proposes and votes {1, 2}, and ignores the later {1, 2, 3} alerts.
    src = h.swarm.endpoint(5)
    partial = tuple(
        AlertMessage(
            edge_src=src,
            edge_dst=h.swarm.endpoint(int(v)),
            edge_status=EdgeStatus.DOWN,
            configuration_id=cluster.get_current_configuration_id(),
            ring_numbers=tuple(range(10)),
        )
        for v in (1, 2)
    )
    h.network.deliver(
        src, cluster.listen_address, BatchedAlertMessage(src, partial), 1000
    )
    h.scheduler.run_for(300)  # real member proposes {1,2} and votes it
    slot = h.swarm._slot_of[cluster.listen_address]
    assert slot in h.swarm.sim._extern_voted, "conflicting vote not registered"
    # fast round: 12 votes for {1,2,3} + 1 for {1,2} -- no value reaches 13
    rec = h.swarm.pump(max_rounds=32, classic_fallback_after_rounds=None)
    assert rec is None, "conflicting vote must block the fast quorum"
    # the classic round picks the majority value (> N/4 rule) and decides
    rec = h.swarm.pump(max_rounds=16, classic_fallback_after_rounds=4)
    assert rec is not None and rec.via_classic_round
    assert sorted(rec.cut) == [1, 2, 3]
    assert h.swarm.sim.membership_size == 13


def test_extern_row_overflow_warns_and_converges_via_fallback(caplog):
    """Degraded mode of the extern-proposal-row cap (VERDICT r3 item 4,
    driver.py register_extern_vote): six real members vote six DISTINCT cuts
    against extern_proposals=4 -- the 5th and 6th distinct values find no
    free row, the overflow warning fires, those votes are dropped
    (protocol-legal best-effort loss, every vote in the reference is), and
    the stalled fast round still converges through the classic fallback on
    the majority value."""
    import logging

    from rapid_tpu.types import AlertMessage, BatchedAlertMessage, EdgeStatus

    h = BridgeHarness(n_virtual=15, capacity=26, seed=13)
    members = [h.join_real_node(f"real-{i}")[0] for i in range(6)]
    assert h.swarm.sim.config.extern_proposals == 4  # the bridge default
    victims = np.array([1, 2, 3])
    h.swarm.sim.crash(victims)
    # each real member receives full-ring evidence for a DIFFERENT subset of
    # the victims before the swarm's own broadcast: its detector crosses H on
    # that subset, latches it as its proposal, and votes it -- six distinct
    # values for four extern rows
    subsets = [(1,), (2,), (3,), (1, 2), (1, 3), (2, 3)]
    src = h.swarm.endpoint(5)
    for cluster, subset in zip(members, subsets):
        evidence = tuple(
            AlertMessage(
                edge_src=src,
                edge_dst=h.swarm.endpoint(int(v)),
                edge_status=EdgeStatus.DOWN,
                configuration_id=cluster.get_current_configuration_id(),
                ring_numbers=tuple(range(10)),
            )
            for v in subset
        )
        h.network.deliver(
            src, cluster.listen_address,
            BatchedAlertMessage(src, evidence), 1000,
        )
    with caplog.at_level(logging.WARNING, logger="rapid_tpu.sim.driver"):
        h.scheduler.run_for(400)  # members propose + vote their subsets
    assert len(h.swarm.sim._extern_rows) == 4, "first four values interned"
    # each overflowing vote warns once per delivered copy (the member
    # broadcast it to every swarm endpoint); exactly the 5th and 6th
    # members' slots overflow
    overflow_slots = {
        r.args[-1]
        for r in caplog.records
        if "no free extern proposal row" in r.message
    }
    expected = {h.swarm._slot_of[m.listen_address] for m in members[4:]}
    assert overflow_slots == expected, "5th and 6th distinct values must warn"
    # fast round: 12 simulated votes for {1,2,3}, six real votes scattered
    # over other values -- no value reaches the quorum of 16 (N=21)
    rec = h.swarm.pump(max_rounds=32, classic_fallback_after_rounds=None)
    assert rec is None, "scattered votes must stall the fast round"
    rec = h.swarm.pump(max_rounds=16, classic_fallback_after_rounds=4)
    assert rec is not None and rec.via_classic_round
    assert sorted(rec.cut) == [1, 2, 3]
    assert h.swarm.sim.membership_size == 18  # 21 - the 3 victims


def test_lagging_member_caught_up_after_lost_decision():
    """A member whose decision delivery was lost must not stay behind
    forever: its next alert traffic is stamped with the pre-decision
    configuration id, and the bridge replays the decision packet
    (alerts + quorum votes) to it."""
    h = BridgeHarness(n_virtual=24, seed=10)
    cluster, _ = h.join_real_node("real-1")
    member = cluster.listen_address
    slot = h.swarm._real[member]
    # crash three of the member's own monitored subjects, so its FDs will
    # later produce DOWN alerts (config-stamped traffic) about them
    subjects = np.asarray(h.swarm.sim.state.subjects)[slot]
    victims = np.unique(subjects)[:3]
    config_before = cluster.get_current_configuration_id()

    # lose every swarm->member delivery while the decision happens
    lift = h.network.add_filter(lambda s, d, m: d != member)
    h.swarm.sim.crash(victims)
    rec = h.swarm.pump(max_rounds=32)
    assert rec is not None and sorted(rec.cut) == sorted(int(v) for v in victims)
    h.scheduler.run_for(300)
    assert cluster.get_membership_size() == 25  # still on the old view
    assert cluster.get_current_configuration_id() == config_before

    # heal the link; the member's own FD crosses threshold on its dead
    # subjects and broadcasts DOWN alerts stamped with the old config id,
    # which triggers the replay
    lift()
    h.scheduler.run_for(15_000)
    assert cluster.get_membership_size() == 22
    assert cluster.get_current_configuration_id() == h.swarm.sim.configuration_id()


def test_lagging_member_walked_forward_through_packet_history():
    """A live member unreachable across TWO consecutive decisions is walked
    FORWARD packet by packet when deliveries resume (bridge packet history
    + pump reconciliation), instead of being cut: FastPaxos is
    per-configuration, so each missed decision must be replayed in order.
    Regression pin for the round-5 'staircase' (members stranded at their
    join-era configuration once decisions outpaced their chains)."""
    h = BridgeHarness(n_virtual=24, capacity=32, seed=6)
    cluster, _ = h.join_real_node("10.9.9.1", 9100)
    member_ep = Endpoint.from_parts("10.9.9.1", 9100)
    assert cluster.get_membership_size() == 25

    # the member stays alive and listening, but nothing reaches it
    lift = h.network.add_filter(lambda s, d, m: d != member_ep)

    def decide(victim):
        h.swarm.sim.crash(np.array([victim]))
        for _ in range(40):
            rec = h.swarm.pump()
            h.scheduler.run_for(2_000)
            if rec is not None:
                return rec
        raise AssertionError("no decision")

    decide(2)
    decide(3)
    # chains to the member failed (5s deadline x retries, on virtual time);
    # it is now two configurations behind
    assert cluster.get_membership_size() == 25
    swarm_config = h.swarm.sim.configuration_id()
    assert cluster.get_current_configuration_id() != swarm_config

    lift()
    # reconciliation re-drives the FIRST missed packet; its settle walks the
    # member through the second -- no cut, no rejoin
    for _ in range(60):
        h.swarm.pump()
        h.scheduler.run_for(2_000)
        if (
            cluster.get_membership_size() == 23
            and cluster.get_current_configuration_id() == swarm_config
        ):
            break
    assert cluster.get_membership_size() == 23
    assert cluster.get_current_configuration_id() == swarm_config
    # the member was repaired in place: still an active seat, never cut
    slot = h.swarm._slot_of[member_ep]  # noqa: SLF001
    assert h.swarm.sim.active[slot] and h.swarm.sim.alive[slot]


def test_member_beyond_packet_history_is_cut_for_rejoin():
    """The walking repair has a horizon: a member unreachable across MORE
    decisions than the packet history holds (8) cannot be walked forward
    (its oldest missed packet is gone), so it is cut for rejoin -- Rapid's
    answer to a node that falls behind."""
    h = BridgeHarness(n_virtual=24, capacity=32, seed=7)
    cluster, _ = h.join_real_node("10.9.9.2", 9200)
    member_ep = Endpoint.from_parts("10.9.9.2", 9200)
    slot = h.swarm._slot_of[member_ep]  # noqa: SLF001
    lift = h.network.add_filter(lambda s, d, m: d != member_ep)

    def decide(victim):
        h.swarm.sim.crash(np.array([victim]))
        for _ in range(40):
            rec = h.swarm.pump()
            h.scheduler.run_for(2_000)
            if rec is not None:
                return rec
        raise AssertionError("no decision")

    # 9 decisions while the member is unreachable: its first missed packet
    # ages out of the 8-deep history, and reconciliation cuts it
    for victim in range(2, 11):
        decide(victim)
        if not h.swarm.sim.active[slot]:
            break
        # let failed chains settle and reconciliation run
        for _ in range(6):
            h.swarm.pump()
            h.scheduler.run_for(3_000)
    for _ in range(60):
        rec = h.swarm.pump()
        h.scheduler.run_for(2_000)
        if not h.swarm.sim.active[slot]:
            break
    assert not h.swarm.sim.active[slot] or not h.swarm.sim.alive[slot], (
        "member beyond the packet history was never cut"
    )
    lift()
