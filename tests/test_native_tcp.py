"""Native epoll transport (native/rapid_io.cpp via NativeTcpClientServer):
wire interop with the pure-Python transport, load behavior, BOOTSTRAPPING
semantics, and a live real-time cluster running entirely on the native
server half -- the runtime-IO parity surface for the reference's Netty
event-loop transport (NettyClientServer.java, SharedResources.java:63-67).
"""

import threading
import time

import pytest

from rapid_tpu import ClusterBuilder, Endpoint, NodeId, Settings
from rapid_tpu.messaging.native_tcp import (
    NativeTcpClientServer,
    native_io_available,
)
from rapid_tpu.messaging.tcp import TcpClientServer
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.runtime.futures import Promise
from rapid_tpu.types import (
    NodeStatus,
    PreJoinMessage,
    ProbeMessage,
    ProbeResponse,
    Response,
)

pytestmark = pytest.mark.skipif(
    not native_io_available(), reason="librapid_io.so unavailable (no toolchain)"
)

NID = NodeId(424242, -171717)


@pytest.fixture
def port_base():
    from harness import free_port_base

    return free_port_base(8)


class EchoService:
    def __init__(self):
        self.count = 0
        self.lock = threading.Lock()

    def handle_message(self, msg):
        with self.lock:
            self.count += 1
        if isinstance(msg, ProbeMessage):
            return Promise.completed(ProbeResponse(NodeStatus.OK))
        return Promise.completed(Response())


def test_python_clients_against_native_server(port_base):
    """Wire interop: 20 pure-Python clients x 5 requests against one native
    server (NettyClientServerTest.java:41-81 at the same load)."""
    server_addr = Endpoint.from_parts("127.0.0.1", port_base)
    server = NativeTcpClientServer(server_addr)
    service = EchoService()
    server.set_membership_service(service)
    server.start()
    try:
        clients = [
            TcpClientServer(Endpoint.from_parts("127.0.0.1", port_base + 1 + i))
            for i in range(20)
        ]
        promises = [
            c.send_message(server_addr, ProbeMessage(sender=c.address))
            for c in clients
            for _ in range(5)
        ]
        for p in promises:
            assert p.result(10) == ProbeResponse(NodeStatus.OK)
        assert service.count == 100
        for c in clients:
            c.shutdown()
    finally:
        server.shutdown()


def test_native_client_against_python_server(port_base):
    """The inherited client half interoperates with the Python server."""
    server_addr = Endpoint.from_parts("127.0.0.1", port_base)
    server = TcpClientServer(server_addr)
    server.set_membership_service(EchoService())
    server.start()
    native = NativeTcpClientServer(Endpoint.from_parts("127.0.0.1", port_base + 1))
    native.start()
    try:
        p = native.send_message(server_addr, ProbeMessage(sender=native.address))
        assert p.result(10) == ProbeResponse(NodeStatus.OK)
    finally:
        native.shutdown()
        server.shutdown()


def test_bootstrapping_before_service_wired_native(port_base):
    """GrpcServer.java:83-95 semantics on the native server: probes answered
    BOOTSTRAPPING before set_membership_service, everything else dropped."""
    addr = Endpoint.from_parts("127.0.0.1", port_base)
    server = NativeTcpClientServer(addr)
    server.start()
    client = TcpClientServer(Endpoint.from_parts("127.0.0.1", port_base + 1))
    try:
        p = client.send_message_best_effort(addr, ProbeMessage(sender=client.address))
        assert p.result(10) == ProbeResponse(NodeStatus.BOOTSTRAPPING)
        settings = Settings(message_timeout_ms=200)
        fast_client = TcpClientServer(
            Endpoint.from_parts("127.0.0.1", port_base + 2), settings
        )
        p2 = fast_client.send_message_best_effort(
            addr, PreJoinMessage(sender=fast_client.address, node_id=NID)
        )
        with pytest.raises(TimeoutError):
            p2.result(5)
        fast_client.shutdown()
    finally:
        client.shutdown()
        server.shutdown()


def test_ephemeral_port_adopted(port_base):
    """Binding port 0 adopts the kernel-assigned port into the address."""
    server = NativeTcpClientServer(Endpoint.from_parts("127.0.0.1", 0))
    server.set_membership_service(EchoService())
    server.start()
    try:
        assert server.address.port > 0
        client = TcpClientServer(Endpoint.from_parts("127.0.0.1", port_base))
        p = client.send_message(server.address, ProbeMessage(sender=client.address))
        assert p.result(10) == ProbeResponse(NodeStatus.OK)
        client.shutdown()
    finally:
        server.shutdown()


def test_peer_senses_native_shutdown_by_eof(port_base):
    """shutdown() FINs accepted connections (the shutdown-before-close dance):
    a client's reader thread sees EOF promptly and fails its outstanding
    requests instead of hanging until the deadline."""
    addr = Endpoint.from_parts("127.0.0.1", port_base)
    server = NativeTcpClientServer(addr)
    server.set_membership_service(EchoService())
    server.start()
    client = TcpClientServer(Endpoint.from_parts("127.0.0.1", port_base + 1))
    try:
        p = client.send_message(addr, ProbeMessage(sender=client.address))
        assert p.result(10) == ProbeResponse(NodeStatus.OK)
        conn = client._connection(addr)  # noqa: SLF001 -- liveness probe
        server.shutdown()
        deadline = time.time() + 5
        while time.time() < deadline and not conn.closed:
            time.sleep(0.02)
        assert conn.closed, "client never observed the server's FIN"
    finally:
        client.shutdown()
        server.shutdown()


def test_real_time_cluster_on_native_transport(port_base):
    """A live 3-node cluster entirely on the native transport: join,
    converge, crash one, converge again (tier-3 shape over epoll)."""
    blacklist = set()
    settings = Settings(
        failure_detector_interval_ms=30,
        batching_window_ms=10,
        consensus_fallback_base_delay_ms=200,
    )

    def build(i, seed=None):
        addr = Endpoint.from_parts("127.0.0.1", port_base + i)
        transport = NativeTcpClientServer(addr, settings)
        builder = (
            ClusterBuilder(addr)
            .use_settings(settings)
            .set_messaging_client_and_server(transport, transport)
            .set_edge_failure_detector_factory(StaticFailureDetectorFactory(blacklist))
        )
        if seed is None:
            return builder.start()
        return builder.join(seed, timeout=30)

    seed = build(0)
    c1 = build(1, seed.listen_address)
    c2 = build(2, seed.listen_address)
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            if (
                seed.get_membership_size()
                == c1.get_membership_size()
                == c2.get_membership_size()
                == 3
            ):
                break
            time.sleep(0.05)
        assert seed.get_membership_size() == 3
        assert seed.get_memberlist() == c1.get_memberlist() == c2.get_memberlist()

        blacklist.add(c2.listen_address)
        c2.shutdown()
        deadline = time.time() + 30
        while time.time() < deadline:
            if seed.get_membership_size() == 2 and c1.get_membership_size() == 2:
                break
            time.sleep(0.05)
        assert seed.get_membership_size() == 2
        assert c1.get_membership_size() == 2
    finally:
        seed.shutdown()
        c1.shutdown()


def test_send_never_blocks_on_stalled_peer(port_base):
    """A peer that stops reading must not block send(): bytes queue in the
    reactor and flush on EPOLLOUT once the peer drains -- intact and in
    order. (The Python server isolates slow peers with a thread per
    connection; the reactor must preserve that property on one thread.)"""
    import socket as pysocket
    import struct

    from rapid_tpu.runtime.native_io import NativeReactor

    reactor = NativeReactor("127.0.0.1", 0)
    try:
        sock = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_STREAM)
        sock.setsockopt(pysocket.SOL_SOCKET, pysocket.SO_RCVBUF, 4096)
        sock.connect(("127.0.0.1", reactor.port))
        # announce the connection to the reactor by sending one tiny frame
        sock.sendall(struct.pack("!I", 3) + b"hi!")
        ev, conn_id, payload = reactor.poll(timeout_ms=5000)
        assert ev == 1 and payload == b"hi!"

        # 200 x 64KiB responses (~12.8 MB) into a peer that is not reading:
        # every send must return promptly (the stall budget here is the test
        # timeout, not a per-send block)
        chunk = bytes(range(256)) * 256  # 64 KiB
        t0 = time.time()
        for _ in range(200):
            assert reactor.send(conn_id, chunk)
        assert time.time() - t0 < 5.0, "send() blocked on a stalled peer"

        # now drain: all 200 frames arrive intact and in order
        def read_exactly(n):
            buf = bytearray()
            while len(buf) < n:
                got = sock.recv(n - len(buf))
                assert got, "connection died mid-drain"
                buf.extend(got)
            return bytes(buf)

        sock.settimeout(30)
        for i in range(200):
            (length,) = struct.unpack("!I", read_exactly(4))
            assert length == len(chunk), f"frame {i} length {length}"
            assert read_exactly(length) == chunk, f"frame {i} corrupted"
        sock.close()
    finally:
        reactor.shutdown()


def test_oversized_frame_kills_only_that_connection(port_base):
    """A frame claiming > 64 MiB is a protocol violation: the reactor drops
    that connection (like tcp.py's ValueError path) while other connections
    keep working."""
    import socket as pysocket
    import struct

    from rapid_tpu.runtime.native_io import NativeReactor

    reactor = NativeReactor("127.0.0.1", 0)
    try:
        bad = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_STREAM)
        bad.connect(("127.0.0.1", reactor.port))
        good = pysocket.socket(pysocket.AF_INET, pysocket.SOCK_STREAM)
        good.connect(("127.0.0.1", reactor.port))

        bad.sendall(struct.pack("!I", (64 << 20) + 1))  # oversized claim
        # the violator gets closed: its next recv sees EOF
        bad.settimeout(10)
        assert bad.recv(1) == b""

        good.sendall(struct.pack("!I", 5) + b"hello")
        deadline = time.time() + 10
        seen = None
        while time.time() < deadline:
            ev, conn_id, payload = reactor.poll(timeout_ms=500)
            if ev == 1:
                seen = payload
                break
        assert seen == b"hello", "healthy connection was disturbed"
        good.close()
        bad.close()
    finally:
        reactor.shutdown()


@pytest.mark.slow
def test_reactor_tsan_stress_clean():
    """Dynamic race validation: build the reactor + stress harness under
    ThreadSanitizer and run it (concurrent connects, echoing pollers,
    abrupt disconnects, shutdown racing in-flight sends). Skips where the
    toolchain lacks libtsan. The reference's race story is static-only;
    the native component gets a dynamic one."""
    import os
    import subprocess
    import tempfile

    native_dir = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
    cxx = os.environ.get("CXX", "g++")  # probe with the compiler make uses
    with tempfile.NamedTemporaryFile(suffix=".cpp", mode="w", delete=False) as f:
        f.write("int main(){return 0;}")
        probe_src = f.name
    try:
        try:
            probe = subprocess.run(
                [cxx, "-fsanitize=thread", "-o", probe_src + ".bin", probe_src],
                capture_output=True,
            )
        except FileNotFoundError:
            pytest.skip(f"no such compiler: {cxx}")
        if probe.returncode != 0:
            pytest.skip("toolchain lacks ThreadSanitizer")
    finally:
        for p in (probe_src, probe_src + ".bin"):
            if os.path.exists(p):
                os.unlink(p)
    try:
        result = subprocess.run(
            ["make", "-C", native_dir, "stress-tsan"],
            capture_output=True, text=True, timeout=300,
        )
    except FileNotFoundError:
        pytest.skip("make not installed")
    assert result.returncode == 0, result.stdout + result.stderr
    assert "stress ok" in result.stdout
