"""Heterogeneous alert delivery: almost-everywhere agreement made real.

With delivery groups, different node classes can miss different broadcast
subsets, hold diverging cut-detector states, and propose different cuts --
the scenario Rapid's H/L filter + 3/4 supermajority exist to survive
(paper §4-5). These tests pin down the consensus semantics under divergence.
"""

import numpy as np

from rapid_tpu.sim.driver import Simulator
from rapid_tpu.sim.engine import SimConfig


def make(n, groups, group_split, seed=0, capacity=None):
    """Simulator with the first ``group_split`` nodes in group 0, rest group 1."""
    config = SimConfig(capacity=capacity or n, groups=groups)
    sim = Simulator(n, capacity=capacity, config=config, seed=seed)
    group_of = np.zeros(config.capacity, dtype=np.int32)
    group_of[group_split:] = 1
    sim.set_delivery_groups(group_of)
    return sim


def test_single_group_matches_default():
    """G=1 with full delivery behaves exactly like the ungrouped engine."""
    a = Simulator(30, seed=3)
    b = make(30, groups=2, group_split=30, seed=3)  # group 1 empty
    for sim in (a, b):
        sim.crash(np.array([5, 6]))
    ra = a.run_until_decision(max_rounds=40)
    rb = b.run_until_decision(max_rounds=40)
    assert set(ra.cut) == set(rb.cut) == {5, 6}
    assert ra.configuration_id == rb.configuration_id
    assert ra.virtual_time_ms == rb.virtual_time_ms


def test_small_blind_minority_does_not_block_decision():
    """A minority group that misses every alert never announces; the seeing
    supermajority still reaches the 3/4 quorum -- almost-everywhere agreement
    (paper §4: the cut commits without unanimity)."""
    n = 40
    sim = make(n, groups=2, group_split=36, seed=4)  # 4 blind nodes
    victim = np.array([10])
    sim.crash(victim)
    # group 1 hears nothing from anyone
    sim.drop_broadcasts(1, np.arange(n))
    rec = sim.run_until_decision(max_rounds=40, classic_fallback_after_rounds=None)
    assert rec is not None, "36/40 identical votes meet quorum 40-9=31"
    assert list(rec.cut) == [10]


def test_large_blind_minority_blocks_fast_path_then_classic_recovers():
    """If more than F = floor((N-1)/4) members never announce, the fast round
    cannot decide; the classic recovery round among the live majority picks
    the announced proposal."""
    n = 40
    sim = make(n, groups=2, group_split=28, seed=5)  # 12 blind > F=9
    victim = np.array([10])
    sim.crash(victim)
    sim.drop_broadcasts(1, np.arange(n))
    # no fast decision possible: 27 live announced votes < quorum 31
    rec_stalled = sim.run_until_decision(
        max_rounds=24, classic_fallback_after_rounds=None
    )
    assert rec_stalled is None
    rec = sim.run_until_decision(max_rounds=24, classic_fallback_after_rounds=4)
    assert rec is not None and rec.via_classic_round
    assert list(rec.cut) == [10]


def test_in_flux_group_blocks_fast_path_until_classic_round():
    """A group that misses broadcasts from 3 of the victim's 10 observers
    collects only 7 reports -- inside the [L=4, H=9) flux band -- so it never
    announces. With 10 of 40 members stuck (> F = 9), no identical-proposal
    pool reaches the quorum of 31: the fast path genuinely blocks under
    diverging views, and the classic recovery round picks the announced
    proposal."""
    n = 40
    sim = make(n, groups=2, group_split=30, seed=6)
    victim = 10
    sim.crash(np.array([victim]))
    # group 1 (10 nodes) misses broadcasts from 3 observers of the victim
    observers = np.asarray(sim.state.observers)[victim][:3]
    sim.drop_broadcasts(1, observers)
    rec = sim.run_until_decision(max_rounds=40, classic_fallback_after_rounds=None)
    assert rec is None  # group 0's 29-30 live votes < quorum 31
    # and the classic round resolves it
    rec = sim.run_until_decision(max_rounds=10, classic_fallback_after_rounds=2)
    assert rec is not None and rec.via_classic_round
    assert list(rec.cut) == [victim]


def test_two_groups_identical_views_pool_votes():
    """Groups with identical proposals pool their votes: 2 groups seeing
    everything decide on the fast path immediately."""
    n = 40
    sim = make(n, groups=2, group_split=20, seed=7)
    sim.crash(np.array([3, 4]))
    rec = sim.run_until_decision(max_rounds=40, classic_fallback_after_rounds=None)
    assert rec is not None and not rec.via_classic_round
    assert set(rec.cut) == {3, 4}


def test_grouped_sharded_matches_single_device():
    """The sharded engine agrees with the single-device engine under
    heterogeneous delivery."""


    from rapid_tpu.shard.engine import (
        make_mesh,
        make_sharded_run,
        place_inputs,
        place_state,
    )
    from rapid_tpu.sim.engine import const_inputs, initial_state, run_rounds_const
    from rapid_tpu.sim.topology import VirtualCluster

    c = 64
    cfg = SimConfig(capacity=c, groups=2)
    vc = VirtualCluster.synthesize(c, cfg.k, seed=8)
    active = np.ones(c, dtype=bool)
    # blind minority of 8 < F = floor(63/4) = 15, so the fast path decides
    group_of = np.zeros(c, dtype=np.int32)
    group_of[56:] = 1
    state = initial_state(cfg, vc, active, seed=8, group_of=group_of)
    alive = active.copy()
    alive[[5]] = False
    deliver = np.ones((2, c), dtype=bool)
    deliver[1, :] = False  # group 1 fully blind
    inputs = const_inputs(cfg, alive, deliver=deliver)

    single = run_rounds_const(cfg, state, inputs, 14)
    mesh = make_mesh(8)
    run = make_sharded_run(cfg, mesh, rounds=14)
    sharded = run(place_state(state, mesh), place_inputs(inputs, mesh))

    assert bool(single.decided) == bool(sharded.decided) == True  # noqa: E712
    np.testing.assert_array_equal(
        np.asarray(single.proposal), np.asarray(sharded.proposal)
    )
    assert int(single.decided_group) == int(sharded.decided_group)
