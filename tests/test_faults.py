"""Nemesis fault plane + hardened retry machinery.

Pins the ISSUE acceptance criteria: decision streams are deterministic and
replayable from the plan seed alone; `call_with_retries` backoff/deadline
behavior is exact on the virtual clock; and one seeded FaultPlan replayed on
(a) the in-process transport, (b) the TCP transport, and (c) the device
plane's fault arrays yields identical cut sets and configuration ids.
"""

import random
import time

import pytest

from harness import ClusterHarness, free_port_base
from rapid_tpu import ClusterBuilder, Endpoint, Settings
from rapid_tpu.faults import (
    EGRESS,
    INGRESS,
    FaultPlan,
    Nemesis,
    UnsupportedDeviceFault,
    _device_rules,
    replay_on_simulator,
)
from rapid_tpu.messaging.retries import (
    RetryDeadlineExceeded,
    RetryPolicy,
    call_with_retries,
)
from rapid_tpu.messaging.tcp import TcpClientServer
from rapid_tpu.observability import Metrics, global_metrics
from rapid_tpu.runtime.futures import Promise
from rapid_tpu.runtime.scheduler import RealScheduler, VirtualScheduler
from rapid_tpu.types import ProbeMessage, Response

A = Endpoint.from_parts("10.0.0.1", 50)
B = Endpoint.from_parts("10.0.0.2", 50)


# ---------------------------------------------------------------------------
# call_with_retries: exact virtual-time schedules
# ---------------------------------------------------------------------------


def _scripted_attempt(scheduler, outcomes):
    """attempt() recording each call's virtual time; outcomes are popped in
    order -- an Exception fails the promise, anything else completes it."""
    times = []

    def attempt():
        times.append(scheduler.now_ms())
        out = outcomes.pop(0)
        p = Promise()
        if isinstance(out, Exception):
            p.try_set_exception(out)
        else:
            p.try_set_result(out)
        return p

    return attempt, times


def test_backoff_schedule_exact_in_virtual_time():
    sched = VirtualScheduler()
    attempt, times = _scripted_attempt(
        sched, [RuntimeError("x")] * 4 + ["ok"]
    )
    p = call_with_retries(
        attempt, 4, scheduler=sched,
        policy=RetryPolicy(base_delay_ms=100, max_delay_ms=1000, jitter="none"),
    )
    assert sched.run_until(p.done, timeout_ms=60_000)
    assert p.exception() is None and p.peek() == "ok"
    # doubling from the base, uncapped within this horizon
    assert times == [0, 100, 300, 700, 1500]


def test_backoff_respects_max_delay_cap():
    sched = VirtualScheduler()
    attempt, times = _scripted_attempt(
        sched, [RuntimeError("x")] * 4 + ["ok"]
    )
    p = call_with_retries(
        attempt, 4, scheduler=sched,
        policy=RetryPolicy(base_delay_ms=100, max_delay_ms=300, jitter="none"),
    )
    assert sched.run_until(p.done, timeout_ms=60_000)
    assert times == [0, 100, 300, 600, 900]  # 100, 200, 300, 300


def test_retries_exhausted_fails_with_last_error():
    sched = VirtualScheduler()
    last = RuntimeError("final")
    attempt, times = _scripted_attempt(
        sched, [RuntimeError("a"), RuntimeError("b"), last]
    )
    metrics = Metrics()
    p = call_with_retries(
        attempt, 2, scheduler=sched,
        policy=RetryPolicy(base_delay_ms=100, jitter="none"),
        metrics=metrics,
    )
    assert sched.run_until(p.done, timeout_ms=60_000)
    assert p.exception() is last
    assert times == [0, 100, 300]
    assert metrics.get("retry_attempts") == 3
    assert metrics.get("retry_exhausted") == 1


def test_deadline_fails_fast_without_sleeping_past_it():
    sched = VirtualScheduler()
    cause = RuntimeError("down")
    attempt, times = _scripted_attempt(sched, [cause] * 10)
    metrics = Metrics()
    p = call_with_retries(
        attempt, 9, scheduler=sched,
        policy=RetryPolicy(base_delay_ms=100, jitter="none"),
        deadline_ms=250, metrics=metrics,
    )
    assert sched.run_until(p.done, timeout_ms=60_000)
    exc = p.exception()
    assert isinstance(exc, RetryDeadlineExceeded)
    assert exc.__cause__ is cause
    # attempt at 0 fails -> retry at 100 fails -> next delay (200) would land
    # at 300 >= 250: the deadline is declared AT 100, not slept through
    assert times == [0, 100]
    assert sched.now_ms() == 100
    assert metrics.get("retry_deadline_exceeded") == 1


def test_default_policy_is_legacy_immediate_resubscribe():
    calls = []

    def attempt():
        calls.append(1)
        p = Promise()
        if len(calls) < 3:
            p.try_set_exception(RuntimeError("x"))
        else:
            p.try_set_result("ok")
        return p

    # no scheduler, no policy, no deadline: completes synchronously
    p = call_with_retries(attempt, 5)
    assert p.done() and p.peek() == "ok"
    assert len(calls) == 3


def test_backoff_without_scheduler_is_rejected():
    with pytest.raises(AssertionError):
        call_with_retries(
            lambda: Promise.completed(1), 1,
            policy=RetryPolicy(base_delay_ms=10),
        )


def test_decorrelated_jitter_is_seed_deterministic_and_bounded():
    policy = RetryPolicy(base_delay_ms=50, max_delay_ms=10_000)

    def delays(seed):
        rng = random.Random(seed)
        prev, out = 0, []
        for _ in range(16):
            prev = policy.next_delay_ms(prev, rng)
            out.append(prev)
        return out

    assert delays(3) == delays(3)
    assert delays(3) != delays(4)
    seq = delays(3)
    prev = 0
    for d in seq:
        assert 50 <= d <= min(10_000, max(50, prev * 3))
        prev = d


# ---------------------------------------------------------------------------
# FaultPlan / Nemesis decision streams
# ---------------------------------------------------------------------------


def _decision_stream(seed, n=64):
    nem = Nemesis(
        FaultPlan(seed=seed)
        .drop(0.5)
        .duplicate(0.3)
        .reorder(0.25, max_extra_ms=40),
        VirtualScheduler(), metrics=Metrics(),
    ).arm(0)
    return [nem.decide(A, B, ProbeMessage(sender=A), EGRESS) for _ in range(n)]


def test_decision_stream_is_plan_seed_deterministic():
    s1, s2 = _decision_stream(9), _decision_stream(9)
    assert s1 == s2
    assert _decision_stream(9) != _decision_stream(10)
    # the stream actually exercises every fault class
    assert any(d.drop for d in s1) and any(not d.drop for d in s1)
    assert any(d.duplicates for d in s1)
    assert any(d.reordered for d in s1)


def test_decisions_are_independent_of_link_interleaving():
    """Each link owns its sequence numbers: interleaving draws for another
    link must not perturb this link's stream."""
    plain = _decision_stream(9)
    nem = Nemesis(
        FaultPlan(seed=9).drop(0.5).duplicate(0.3).reorder(0.25, max_extra_ms=40),
        VirtualScheduler(), metrics=Metrics(),
    ).arm(0)
    interleaved = []
    for _ in range(64):
        interleaved.append(nem.decide(A, B, ProbeMessage(sender=A), EGRESS))
        nem.decide(B, A, ProbeMessage(sender=B), EGRESS)  # noise on B->A
    assert interleaved == plain


def test_windows_and_flip_flop_schedules():
    plan = (
        FaultPlan(seed=0)
        .drop(1.0, windows=((500, 700),))
        .flip_flop(period_ms=2000, dst=B, start_ms=1000)
    )
    windowed, ff = plan.rules
    assert not windowed.active_at(499)
    assert windowed.active_at(500) and windowed.active_at(699)
    assert not windowed.active_at(700)
    # flip-flop: cut at [1000, 2000), healed [2000, 3000), cut again ...
    assert not ff.active_at(0) and not ff.active_at(999)
    assert ff.active_at(1000) and ff.active_at(1999)
    assert not ff.active_at(2000) and not ff.active_at(2999)
    assert ff.active_at(3000)
    # plan time before the arm epoch (negative) is always fault-free: this
    # is what lets a run bootstrap cleanly before arming the schedule
    assert not windowed.active_at(-1) and not ff.active_at(-1)


class _RecordingClient:
    def __init__(self, scheduler):
        self.sched = scheduler
        self.sent = []  # (virtual time, remote, msg)

    def send_message_best_effort(self, remote, msg):
        self.sent.append((self.sched.now_ms(), remote, msg))
        return Promise.completed(Response())

    def send_message(self, remote, msg):
        return self.send_message_best_effort(remote, msg)

    def shutdown(self):
        pass


def test_nemesis_client_drop_times_out_on_message_timeout():
    sched = VirtualScheduler()
    settings = Settings()
    nem = Nemesis(FaultPlan(seed=1).partition_one_way(dst=B), sched,
                  metrics=Metrics()).arm(0)
    inner = _RecordingClient(sched)
    client = nem.client(inner, address=A, settings=settings)
    p = client.send_message_best_effort(B, ProbeMessage(sender=A))
    sched.run_for(settings.probe_message_timeout_ms - 1)
    assert not p.done() and inner.sent == []
    sched.run_for(2)
    assert p.done() and isinstance(p.exception(), TimeoutError)
    assert inner.sent == []  # dropped on the wire, never forwarded
    assert nem.metrics.get("nemesis_dropped") == 1


def test_nemesis_client_delay_and_duplicate():
    sched = VirtualScheduler()
    nem = Nemesis(
        FaultPlan(seed=1).delay(base_ms=250, dst=B).duplicate(1.0, dst=B),
        sched, metrics=Metrics(),
    ).arm(0)
    inner = _RecordingClient(sched)
    client = nem.client(inner, address=A, settings=Settings())
    msg = ProbeMessage(sender=A)
    p = client.send_message_best_effort(B, msg)
    # the duplicate copy goes out immediately; the original is held 250 ms
    assert [t for t, _, _ in inner.sent] == [0]
    sched.run_for(249)
    assert len(inner.sent) == 1 and not p.done()
    sched.run_for(2)
    assert [t for t, _, _ in inner.sent] == [0, 250]
    assert p.done() and p.exception() is None
    assert nem.metrics.get("nemesis_duplicated") == 1
    assert nem.metrics.get("nemesis_delayed") == 1


def test_nemesis_ingress_drop_applies_at_the_server():
    sched = VirtualScheduler()
    nem = Nemesis(
        FaultPlan(seed=1).partition_one_way(dst=B, at=INGRESS), sched,
        metrics=Metrics(),
    ).arm(0)

    class _Service:
        def __init__(self):
            self.handled = []

        def handle_message(self, msg):
            self.handled.append(msg)
            return Promise.completed(Response())

    class _Server:
        def __init__(self):
            self.service = None

        def start(self):
            pass

        def shutdown(self):
            pass

        def set_membership_service(self, service):
            self.service = service

    service, server = _Service(), _Server()
    wrapped = nem.server(server, B)
    wrapped.set_membership_service(service)
    p = server.service.handle_message(ProbeMessage(sender=A))
    assert service.handled == [] and not p.done()
    assert nem.metrics.get("nemesis_dropped") == 1


# ---------------------------------------------------------------------------
# cluster-level: deterministic replay on the in-process fabric
# ---------------------------------------------------------------------------


def _run_probabilistic_cut(n=4):
    """Bootstrap n nodes on real pingpong FDs, then arm a 70% probe-loss
    fault toward one victim; the cumulative FD threshold cuts it. Returns
    what the survivors decided."""
    h = ClusterHarness(seed=3, use_static_fd=False)
    victim = h.addr(n - 1)
    h.with_faults(
        FaultPlan(seed=11).drop(0.7, dst=victim, msg_types=(ProbeMessage,))
    )
    h.nemesis.arm(epoch_ms=1 << 40)  # dormant during bootstrap
    h.create_cluster(n, parallel=False)
    h.wait_and_verify_agreement(n)
    h.nemesis.arm()
    vic = h.instances.pop(victim)
    try:
        h.wait_and_verify_agreement(n - 1)
        survivor = h.instances[h.addr(0)]
        return (
            tuple(survivor.get_memberlist()),
            survivor.get_current_configuration_id(),
        )
    finally:
        vic.shutdown()
        h.shutdown()


def test_inprocess_probabilistic_faults_replay_identically():
    before = global_metrics().get("nemesis_dropped")
    first = _run_probabilistic_cut()
    assert global_metrics().get("nemesis_dropped") > before
    assert first == _run_probabilistic_cut()


# ---------------------------------------------------------------------------
# device-plane compilation
# ---------------------------------------------------------------------------


def test_device_compilation_validates_rules():
    # absorbed by the round model: fine
    ok = (
        FaultPlan(seed=0)
        .partition_one_way(dst=B)
        .drop(0.2)
        .duplicate(0.5)
        .reorder(0.5)
        .delay(base_ms=5)
    )
    assert [idx for idx, _ in _device_rules(ok, round_ms=1000)] == [0, 1]
    # a delay of a round or more cannot be absorbed
    with pytest.raises(UnsupportedDeviceFault):
        _device_rules(FaultPlan(seed=0).delay(base_ms=1000), round_ms=1000)
    # per-source faults have no device analogue (mask is per destination)
    with pytest.raises(UnsupportedDeviceFault):
        _device_rules(FaultPlan(seed=0).partition_one_way(src=A), round_ms=1000)
    # non-probe-affecting drops do not touch the probe mask
    with pytest.raises(UnsupportedDeviceFault):
        _device_rules(
            FaultPlan(seed=0).drop(0.5, msg_types=(Response,)), round_ms=1000
        )


def test_flip_flop_windows_drive_the_device_fault_arrays():
    from rapid_tpu.sim.driver import Simulator
    from rapid_tpu.faults import apply_plan_at, endpoint_slots

    sim = Simulator(4, seed=2)
    slots = endpoint_slots(sim)
    victim_ep = next(ep for ep, s in slots.items() if s == 3)
    plan = FaultPlan(seed=0).flip_flop(period_ms=2000, dst=victim_ep)
    apply_plan_at(sim, plan, t_ms=500, slots=slots)
    assert sim._ingress_partitioned == {3}
    apply_plan_at(sim, plan, t_ms=1500, slots=slots)  # healed half-period
    assert sim._ingress_partitioned == set()
    apply_plan_at(sim, plan, t_ms=2500, slots=slots)
    assert sim._ingress_partitioned == {3}


# ---------------------------------------------------------------------------
# the flagship: one plan, three planes, identical cuts and config ids
# ---------------------------------------------------------------------------


def _wait_real(predicate, what, deadline_s=60.0):
    end = time.time() + deadline_s
    while time.time() < end:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


class _PortedHarness(ClusterHarness):
    """ClusterHarness on an arbitrary port base, so the in-process run uses
    the exact endpoints the TCP run will bind."""

    def __init__(self, base, **kw):
        self._base = base
        super().__init__(**kw)

    def addr(self, i):
        return Endpoint.from_parts("127.0.0.1", self._base + i)


def test_one_fault_plan_three_planes_identical_decisions():
    """ISSUE acceptance: a seeded FaultPlan (one-way partition of one node)
    replayed on the in-process transport, the TCP transport, and the device
    plane produces the same cut set and the same configuration id."""
    n = 5
    cluster_seed = 77
    base = free_port_base(n)
    victim = Endpoint.from_parts("127.0.0.1", base + n - 1)

    def plan():
        return FaultPlan(seed=7).partition_one_way(dst=victim)

    # (a) in-process transport, virtual time ------------------------------
    h = _PortedHarness(base, seed=cluster_seed, use_static_fd=False)
    h.with_faults(plan())
    h.nemesis.arm(epoch_ms=1 << 40)
    h.start_seed(0)
    for i in range(1, n):
        h.join(i)
        h.wait_and_verify_agreement(i + 1)
    full_cfg = (
        h.instances[h.addr(0)]._membership_service._view.get_configuration()
    )
    h.nemesis.arm()  # plan time zero = now: the partition opens
    vic = h.instances.pop(victim)
    try:
        h.wait_and_verify_agreement(n - 1)
        survivor = h.instances[h.addr(0)]
        ip_members = tuple(survivor.get_memberlist())
        ip_config = survivor.get_current_configuration_id()
    finally:
        vic.shutdown()
        h.shutdown()
    assert victim not in ip_members and len(ip_members) == n - 1

    # (b) TCP sockets, real time: same endpoints, same per-node rng
    # derivation as the harness -> identical NodeIds -----------------------
    srng = random.Random(cluster_seed)
    node_seeds = [srng.getrandbits(64) for _ in range(n)]
    settings = Settings(
        failure_detector_interval_ms=50,
        batching_window_ms=20,
        consensus_fallback_base_delay_ms=200,
        probe_message_timeout_ms=100,
    )
    nem = Nemesis(plan(), RealScheduler(name="nemesis-tcp-test"),
                  metrics=Metrics())
    nem.arm(epoch_ms=nem.scheduler.now_ms() + (1 << 40))
    clusters = []

    def build(i, seed_ep=None):
        addr = Endpoint.from_parts("127.0.0.1", base + i)
        transport = TcpClientServer(addr, settings)
        builder = (
            ClusterBuilder(addr)
            .use_settings(settings)
            .set_messaging_client_and_server(
                nem.client(transport, address=addr, settings=settings),
                nem.server(transport, addr),
            )
            .use_rng(random.Random(node_seeds[i]))
        )
        if seed_ep is None:
            return builder.start()
        return builder.join(seed_ep, timeout=30)

    try:
        clusters.append(build(0))
        for i in range(1, n):
            clusters.append(build(i, clusters[0].listen_address))
            size = i + 1
            _wait_real(
                lambda: all(
                    c.get_membership_size() == size for c in clusters
                ),
                f"TCP join convergence to {size}",
            )
        nem.arm()
        survivors = clusters[:-1]
        _wait_real(
            lambda: all(
                c.get_membership_size() == n - 1 for c in survivors
            ),
            "TCP cut convergence",
        )
        tcp_members = tuple(survivors[0].get_memberlist())
        tcp_config = survivors[0].get_current_configuration_id()
        tcp_ids = (
            survivors[0]._membership_service._view.get_configuration().node_ids
        )
    finally:
        for c in clusters:
            c.shutdown()

    assert set(tcp_ids) == set(full_cfg.node_ids)
    assert tcp_members == ip_members
    assert tcp_config == ip_config

    # (c) device plane: seat the same identities, replay the same plan ----
    from rapid_tpu.sim.driver import Simulator

    identities = [
        (ep.hostname, ep.port, nid.high, nid.low)
        for ep, nid in zip(
            (Endpoint.from_parts("127.0.0.1", base + i) for i in range(n)),
            full_cfg.node_ids,
        )
    ]
    sim = Simulator(n, seed=5, identities=identities)
    records = replay_on_simulator(sim, plan(), duration_ms=40_000)
    assert len(records) == 1
    assert [int(s) for s in records[0].cut] == [n - 1]
    assert records[0].configuration_id == ip_config == tcp_config


# ---------------------------------------------------------------------------
# Trace propagation under faults: duplication, reordering, and one-way drops
# must not corrupt span parenting or leak per-churn state
# ---------------------------------------------------------------------------


def _assert_churn_trace_hygiene(harness):
    """After a converged churn: every member closed its episode (the one
    Optional of per-churn state is None) and cross-node span parenting is
    intact -- every traced alert_batch receive resolves to a REAL fd_signal
    mint somewhere in the cluster, with the consistent (parent, trace) pair.
    A duplicated or reordered delivery can at worst repeat such an edge;
    it can never invent or rewrite one. (With simultaneous detection each
    survivor mints its own root, so several trace ids per node is the
    CORRECT shape here, not a fork.)"""
    services = [
        inst._membership_service for inst in harness.instances.values()
    ]
    minted = {}  # fd_signal span id -> the trace id that mint roots
    for svc in services:
        for s in svc.tracer.spans:
            if s.name == "fd_signal":
                minted[s.span_id] = s.trace_id or s.span_id
    assert minted, "no member recorded an fd_signal for the churn"
    for svc in services:
        assert svc._churn_ctx is None  # no per-churn state survives install
        assert any(
            s.name == "view_change" and s.trace_id in set(minted.values())
            for s in svc.tracer.spans
        ), "a member's view_change did not join any minted churn trace"
        for s in svc.tracer.spans:
            # only spans that carried a REMOTE context (remote_span sets the
            # origin attr from it); an untraced batch degrades to a local
            # root span, which is not a cross-node edge
            if s.name == "alert_batch" and "origin" in s.attrs:
                assert s.parent_id in minted, (
                    f"alert_batch parents under unknown span {s.parent_id}"
                )
                assert s.trace_id == minted[s.parent_id], (
                    "alert_batch trace/parent pair was rewritten in flight"
                )


def test_trace_propagation_survives_duplication_and_reorder():
    from rapid_tpu.observability import DEFAULT_JOURNAL_CAPACITY

    plan = FaultPlan(seed=9).duplicate(0.3).reorder(0.3, max_extra_ms=50)
    harness = ClusterHarness(seed=9).with_faults(plan)
    try:
        harness.create_cluster(5)
        harness.wait_and_verify_agreement(5)
        harness.fail_nodes([harness.addr(4)])
        harness.wait_and_verify_agreement(4, timeout_ms=1_200_000)
        _assert_churn_trace_hygiene(harness)
        for instance in harness.instances.values():
            svc = instance._membership_service
            # duplicated deliveries never grow unbounded observability
            # state: the journal stays within its ring capacity
            assert len(svc.recorder) <= DEFAULT_JOURNAL_CAPACITY
    finally:
        harness.shutdown()


def test_trace_propagation_survives_one_way_drops():
    """One-way loss of alert dissemination between two survivors: every
    batch node 1 sends node 2 is dropped, so node 2 learns of the churn
    from the other members' (traced) batches and votes -- cross-node
    parenting must still resolve and the episode must still close
    everywhere. The plan is armed only after bootstrap (far-future epoch
    during joins, the TCP parity test's pattern), because losing UP alerts
    would starve joiner identities rather than exercise tracing."""
    from rapid_tpu.types import BatchedAlertMessage

    harness = ClusterHarness(seed=13)
    plan = FaultPlan(seed=13).drop(
        1.0, src=harness.addr(1), dst=harness.addr(2),
        msg_types=(BatchedAlertMessage,),
    )
    harness.with_faults(plan)
    harness.nemesis.arm(epoch_ms=1 << 40)  # hold fire during bootstrap
    try:
        harness.create_cluster(6)
        harness.wait_and_verify_agreement(6)
        harness.nemesis.arm()  # the one-way drop starts now
        harness.fail_nodes([harness.addr(5)])
        harness.wait_and_verify_agreement(5, timeout_ms=1_200_000)
        _assert_churn_trace_hygiene(harness)
    finally:
        harness.shutdown()
