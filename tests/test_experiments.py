"""The published experiment harnesses stay runnable: each one's measurement
function executes end-to-end at small scale with its internal parity
assertions armed (the sweeps in BASELINE.md are these same functions at
full scale on TPU)."""

from experiments.join_wave import run_size as join_wave_size
from experiments.scaling_sweep import run_size as scaling_size


def test_join_wave_single_view_change():
    out = join_wave_size(300, 0.01, seed=7)
    assert out["admitted_ok"] and out["wave"] == 3
    # a whole wave lands in ONE view change: join reports arrive in round 1,
    # the vote-delivery hop is round 2, plus the batching window -- the
    # protocol time is size-independent (the bootstrap-batching headline,
    # paper Fig. 5)
    assert out["virtual_ms"] == 2 * 1000 + 100


def test_scaling_sweep_point():
    out = scaling_size(300, seed=7)
    assert out["cut_ok"]
    assert out["virtual_ms"] == 11 * 1000 + 100


def test_message_load_strategies_agree_on_protocol_work():
    from experiments.message_load import run_strategy

    uni = run_strategy("unicast", n=16, crash=1, seed=5)
    gos = run_strategy("gossip", n=16, crash=1, seed=5)
    # the dissemination fabric must not change the protocol work performed
    assert uni["per_type_totals"]["BatchedAlertMessage"] == \
        gos["per_type_totals"]["BatchedAlertMessage"]
    assert uni["per_type_totals"]["FastRoundPhase2bMessage"] == \
        gos["per_type_totals"]["FastRoundPhase2bMessage"]
    # unicast delivers each broadcast exactly once per process; gossip pays
    # the epidemic redundancy on top
    assert "GossipEnvelope" not in uni["per_type_totals"]
    assert gos["per_type_totals"]["GossipEnvelope"] > 0
    assert gos["mean_msgs"] > uni["mean_msgs"]
