"""One fabric, one latency model (VERDICT r3 item 3): the reference sends
alerts, votes, and recovery messages over the same transport
(UnicastToAllBroadcaster.java:46-52 -- one sendRequest RPC for every type in
rapid.proto:9-11), so network delay skews all of them alike. These tests pin
that SimConfig.max_delivery_delay applies to the fast-round vote hop and the
classic recovery exchange, not just the alert stream -- and that delaying one
member's vote delays the decision identically in the simulation plane and
the object plane.
"""

import numpy as np

from harness import ClusterHarness
from rapid_tpu.events import ClusterEvents
from rapid_tpu.sim.classic import ClassicCoordinator
from rapid_tpu.sim.driver import Simulator
from rapid_tpu.sim.engine import SimConfig
from rapid_tpu.types import FastRoundPhase2bMessage

N = 8  # with one crash, quorum = N - (N-1)//4 = 7 = every live vote


def _non_observer_member(sim: Simulator, victim: int) -> int:
    """A live member that observes the victim on zero rings: delaying ALL of
    its broadcasts is behaviorally identical to delaying only its vote (it
    contributes no DOWN alert for this cut), which is what the object-plane
    half of the cross-plane test delays."""
    observers = set(int(o) for o in np.asarray(sim.state.observers)[victim])
    for m in range(N):
        if m != victim and m not in observers:
            return m
    raise AssertionError("no non-observer member for this seed")


def _sim_decision_ms(delay_rounds: int, seed: int = 5) -> int:
    config = SimConfig(capacity=N, fd_interval_ms=100, max_delivery_delay=3)
    sim = Simulator(N, config=config, seed=seed)
    victim = 4
    if delay_rounds:
        m = _non_observer_member(sim, victim)
        sim.delay_broadcasts(0, np.array([m]), delay_rounds)
    sim.crash(np.array([victim]))
    rec = sim.run_until_decision(max_rounds=32, batch=32,
                                 classic_fallback_after_rounds=None)
    assert rec is not None and list(rec.cut) == [victim]
    return rec.virtual_time_ms


def test_sim_vote_delay_shifts_decision_by_exact_rounds():
    """Quorum needs every live vote; the delayed member contributes no alert
    for the cut, so the ONLY thing its delay skews is its vote -- and the
    decision shifts by exactly that many rounds."""
    base = _sim_decision_ms(0)
    for d in (1, 2, 3):
        assert _sim_decision_ms(d) - base == d * 100, f"delay_rounds={d}"


def _object_decision_shift_ms(delay_ms: int, n: int = N) -> int:
    """Virtual time from the failure to the seed's VIEW_CHANGE, with the
    FastRoundPhase2bMessage (and only it) from one live member delayed --
    the per-type filter isolates the vote hop exactly, mirroring the sim
    half's non-observer construction."""
    harness = ClusterHarness(seed=11)
    fired = []
    harness.start_seed(
        0,
        subscriptions=[
            (ClusterEvents.VIEW_CHANGE,
             lambda _cid, _changes: fired.append(harness.scheduler.now_ms()))
        ],
    )
    for i in range(1, n):
        harness.join(i)
    delayed_member = harness.addr(1)
    if delay_ms:
        harness.network.add_delay(
            lambda src, dst, msg: (
                delay_ms
                if isinstance(msg, FastRoundPhase2bMessage)
                and src == delayed_member
                else 0
            )
        )
    fired.clear()
    t_fail = harness.scheduler.now_ms()
    harness.fail_nodes([harness.addr(n - 1)])
    harness.wait_and_verify_agreement(n - 1, poll_ms=10)
    harness.shutdown()
    assert fired, "seed never saw the failure view change"
    return fired[0] - t_fail


def test_cross_plane_vote_delay_parity():
    """Delaying one member's vote by D delays the decision by exactly D in
    BOTH planes (the fabric treats votes like any broadcast; quorum waits
    for the skewed vote)."""
    shift_ms = 300
    obj = _object_decision_shift_ms(shift_ms) - _object_decision_shift_ms(0)
    sim = _sim_decision_ms(3) - _sim_decision_ms(0)  # 3 rounds x 100 ms
    assert obj == sim == shift_ms, f"object shifted {obj}, sim {sim}"


def _stalled_sim_with_delay(slow_acceptors: int):
    """A genuinely stalled fast round (blind delivery class > F members) on a
    latency-enabled config, with ``slow_acceptors`` acceptors' responses to
    group 0 (the coordinator's group) one round late."""
    n = 1000
    config = SimConfig(capacity=n, groups=2, max_delivery_delay=1)
    sim = Simulator(n, config=config, seed=7)
    group_of = np.zeros(n, dtype=np.int32)
    group_of[n - 260:] = 1
    sim.set_delivery_groups(group_of)
    victims = np.array([5, 6])
    sim.crash(victims)
    sim.drop_broadcasts(1, np.arange(n))  # group 1 hears nothing: stall
    if slow_acceptors:
        # slot 0 (the coordinator below) is NOT delayed, so its 1a/2a
        # broadcasts land on time and only the response legs are slow
        sim.delay_broadcasts(0, np.arange(1, 1 + slow_acceptors), 1)
    rec = sim.run_until_decision(max_rounds=16,
                                 classic_fallback_after_rounds=None)
    assert rec is None, "fast round must stall for these tests"
    return sim, victims


def test_classic_exchange_bills_flat_hops_without_skew():
    sim, victims = _stalled_sim_with_delay(slow_acceptors=0)
    live = np.flatnonzero(sim.active & sim.alive)
    c = ClassicCoordinator(sim, round_no=2, slot=int(live[0]))
    assert c.phase1() and c.phase2(c.pick_value()) == 0
    assert c.elapsed_rounds == 4  # 1a/1b/2a/2b, one round per hop


def test_classic_exchange_bills_majority_cutoffs_under_skew():
    """With 598 of the 998 live acceptors' responses one round late, the
    coordinator's majority (>500) completes only when the slow responses
    land: each phase closes at cutoff 3 instead of 2, and the recovery still
    decides the stalled cut -- latency skews recovery, it never breaks it."""
    sim, victims = _stalled_sim_with_delay(slow_acceptors=600)
    c = ClassicCoordinator(sim, round_no=2, slot=0)
    assert c.phase1()
    row = c.pick_value()
    assert row == 0 and c.phase2(row) == 0
    assert c.elapsed_rounds == 6  # two phases, each cut off at round 3
    np.testing.assert_array_equal(
        np.flatnonzero(np.asarray(sim.state.proposal)[0]), victims
    )
