"""Direct JVM interop over the wire-compatible gRPC transport (opt-in).

The strongest possible parity claim: the UNTOUCHED reference agent
(standalone-agent.jar, StandaloneAgent.java:94-116) joins a rapid-tpu seed
over real sockets, speaking the reference's own rapid.proto bytes against
our programmatically-built schema. Skips cleanly when no java toolchain or
jar is present (none exists in the default build environment -- the golden
vectors' JVM chain is transitive there; this test makes it direct wherever
a JVM is available).

Run with:
    RAPID_TPU_JVM_JAR=/path/to/standalone-agent.jar python -m pytest \
        tests/test_jvm_interop.py -v
"""

import os
from harness import free_port_base
import shutil
import subprocess
import time

import pytest

JAR = os.environ.get("RAPID_TPU_JVM_JAR", "")

pytestmark = pytest.mark.skipif(
    not (JAR and os.path.exists(JAR) and shutil.which("java")),
    reason="JVM interop is opt-in: set RAPID_TPU_JVM_JAR to the reference's "
    "standalone-agent.jar with a java runtime on PATH",
)


def test_reference_jvm_agent_joins_rapid_tpu_seed():
    from rapid_tpu import ClusterBuilder, Endpoint, Settings
    from rapid_tpu.messaging.grpc_transport import GrpcClient, GrpcServer

    settings = Settings()
    seed = None
    # retry over probed free port pairs: an occupied port (either the
    # seed's or the JVM agent's) must not fail the opt-in test spuriously
    for _ in range(5):
        base = free_port_base(2)
        seed_addr = Endpoint.from_parts("127.0.0.1", base)
        try:
            seed = (
                ClusterBuilder(seed_addr)
                .use_settings(settings)
                .set_messaging_client_and_server(
                    GrpcClient(seed_addr, settings), GrpcServer(seed_addr)
                )
                .start()
            )
            break
        except OSError:
            continue
    assert seed is not None, "no free port pair in 5 attempts"
    proc = subprocess.Popen(
        [
            shutil.which("java"), "-jar", JAR,
            "--listenAddress", f"127.0.0.1:{base + 1}",
            "--seedAddress", f"127.0.0.1:{base}",
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    try:
        deadline = time.time() + 60
        while time.time() < deadline and seed.get_membership_size() != 2:
            assert proc.poll() is None, "JVM agent exited before joining"
            time.sleep(0.5)
        assert seed.get_membership_size() == 2
        members = seed.get_memberlist()
        assert Endpoint.from_parts("127.0.0.1", base + 1) in members
    finally:
        proc.kill()
        proc.wait(timeout=10)
        seed.shutdown()
