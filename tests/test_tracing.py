"""Cross-node trace propagation, introspection RPC, and flight recorder.

Pins the ISSUE acceptance criteria: the wire schema carries an OPTIONAL
trace context that old decoders ignore (any "__"-prefixed envelope key is
stripped before the dataclass constructs); a three-node in-process churn
yields ONE trace id spanning fd_signal on the detecting node through
view_change on every member, and tools/tracecat.py merges the per-node
Chrome traces so that episode reads end to end; and every member's
ClusterStatusRequest answers agree on the configuration id -- including
through an armed nemesis.
"""

import json

import msgpack

from harness import ClusterHarness
from rapid_tpu.faults import FaultPlan
from rapid_tpu.messaging.codec import ENVELOPE, decode, encode
from rapid_tpu.messaging.inprocess import InProcessClient
from rapid_tpu.monitoring.static_fd import StaticFailureDetectorFactory
from rapid_tpu.observability import (
    FlightRecorder,
    Span,
    TraceContext,
    Tracer,
    chrome_trace,
    stamp_trace_context,
    trace_context_of,
)
from rapid_tpu.types import (
    ClusterStatusRequest,
    ClusterStatusResponse,
    Endpoint,
    ProbeMessage,
)
from tools.tracecat import merge_traces

A_EP = Endpoint.from_parts("10.0.0.1", 50)


# ---------------------------------------------------------------------------
# Wire schema: trace context is an optional, backward-compatible extension
# ---------------------------------------------------------------------------


def test_codec_roundtrips_trace_context():
    msg = ProbeMessage(sender=A_EP)
    stamp_trace_context(msg, TraceContext(7, 9, origin="10.0.0.1:50"))
    request_no, decoded = decode(encode(3, msg))
    assert request_no == 3
    assert decoded == msg  # the sidecar is invisible to dataclass equality
    assert trace_context_of(decoded) == TraceContext(7, 9, "10.0.0.1:50", 0)


def test_untraced_frame_has_no_context_and_no_reserved_key():
    frame = encode(1, ProbeMessage(sender=A_EP))
    assert b"__tc" not in frame  # old and new frames are byte-identical
    _, decoded = decode(frame)
    assert trace_context_of(decoded) is None


def test_trace_context_is_a_pure_wire_extension():
    """A stamped frame differs from an unstamped one ONLY by the "__tc"
    envelope key: strip it and the payload bytes are identical, which is
    exactly what an old decoder (which drops unknown "__" keys) sees."""
    plain = encode(1, ProbeMessage(sender=A_EP))
    stamped_msg = ProbeMessage(sender=A_EP)
    stamp_trace_context(stamped_msg, TraceContext(1, 2))
    stamped = encode(1, stamped_msg)
    body = msgpack.unpackb(stamped[ENVELOPE.size:], raw=False)
    assert body.pop("__tc") == [1, 2, "", 0]
    assert msgpack.packb(body, use_bin_type=True) == plain[ENVELOPE.size:]


def test_decoder_strips_unknown_reserved_keys():
    """A frame from a FUTURE peer -- carrying "__tc" plus a reserved key this
    version has never heard of -- must construct cleanly (forward compat,
    same rule that gives old decoders backward compat)."""
    frame = encode(4, ProbeMessage(sender=A_EP))
    body = msgpack.unpackb(frame[ENVELOPE.size:], raw=False)
    body["__tc"] = [5, 6, "peer", 0]
    body["__future_hint"] = {"anything": 1}
    doctored = frame[:ENVELOPE.size] + msgpack.packb(body, use_bin_type=True)
    request_no, decoded = decode(doctored)
    assert request_no == 4
    assert decoded == ProbeMessage(sender=A_EP)
    assert trace_context_of(decoded) == TraceContext(5, 6, "peer", 0)


def test_malformed_wire_context_degrades_to_none():
    assert TraceContext.from_wire(None) is None
    assert TraceContext.from_wire(7) is None
    assert TraceContext.from_wire([1]) is None
    assert TraceContext.from_wire([1, "x", "y", 0]) is None
    assert TraceContext.from_wire([3, 4, "n1", 1]) == TraceContext(3, 4, "n1", 1)


def test_stamping_a_slotted_object_degrades_to_none():
    class Slotted:
        __slots__ = ("x",)

    obj = Slotted()
    stamp_trace_context(obj, TraceContext(1, 2))  # must not raise
    assert trace_context_of(obj) is None


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_is_bounded():
    rec = FlightRecorder(capacity=4, node="n1", clock=lambda: 42)
    for i in range(10):
        rec.record("fd_signal", i=i)
    assert len(rec) == 4  # oldest dropped, recorder can run forever
    assert [e["seq"] for e in rec.tail()] == [7, 8, 9, 10]
    assert [e["seq"] for e in rec.tail(2)] == [9, 10]


def test_flight_recorder_wire_form_and_dump(tmp_path):
    rec = FlightRecorder(node="n1", clock=lambda: 1234)
    rec.record("view_install", configuration_id=7, size=3)
    (line,) = rec.to_wire()
    entry = json.loads(line)
    assert entry["kind"] == "view_install"
    assert entry["node"] == "n1"
    assert entry["seq"] == 1
    assert entry["virtual_ms"] == 1234
    assert entry["detail"] == {"configuration_id": 7, "size": 3}
    assert "wall_s" in entry
    rec.record("status_served", requester="10.0.0.9:1")
    path = tmp_path / "journal.jsonl"
    rec.dump(str(path))
    kinds = [json.loads(l)["kind"] for l in path.read_text().splitlines()]
    assert kinds == ["view_install", "status_served"]


def test_flight_recorder_survives_a_dying_clock():
    def clock():
        raise RuntimeError("scheduler torn down")

    rec = FlightRecorder(node="n1", clock=clock)
    assert rec.record("kicked", configuration_id=1)["virtual_ms"] is None


# ---------------------------------------------------------------------------
# End to end: one trace id from fd_signal to every member's view_change
# ---------------------------------------------------------------------------


def _staggered_churn_cluster():
    """Three nodes; the victim's 10 observer slots all belong to nodes 0 and
    1, each with its OWN static-FD blacklist. Node 0 detects first; node 1
    adopts node 0's churn trace from the alert batch BEFORE its own detector
    fires, so its later fd_signal keeps the adopted context -- one trace id
    across both processes (under simultaneous detection each node would mint
    its own root, which is correct but not the cross-node case this pins)."""
    h = ClusterHarness(seed=7, use_static_fd=False)
    bl0, bl1 = set(), set()
    h.start_seed(0, fd=StaticFailureDetectorFactory(bl0))
    h.join(1, fd=StaticFailureDetectorFactory(bl1))
    h.join(2, fd=StaticFailureDetectorFactory(set()))
    h.wait_and_verify_agreement(3)
    victim = h.addr(2)
    svc0 = h.instances[h.addr(0)]._membership_service
    svc1 = h.instances[h.addr(1)]._membership_service
    h.instances.pop(victim).shutdown()

    bl0.add(victim)  # node 0 detects alone; the cut stays below H
    ok = h.scheduler.run_until(
        lambda: svc1._churn_ctx is not None, timeout_ms=600_000
    )
    assert ok, "node 1 never adopted node 0's churn trace from the batch"
    adopted = svc1._churn_ctx
    bl1.add(victim)  # node 1's own fd_signal fires but keeps the adopted ctx
    # fast path needs N-F = 3 identical votes and only 2 members are live:
    # convergence rides the classic Paxos fallback (expovariate delay)
    h.wait_and_verify_agreement(2, timeout_ms=1_200_000)
    return h, svc0, svc1, adopted


def test_one_trace_spans_fd_signal_to_every_view_change():
    h, svc0, svc1, adopted = _staggered_churn_cluster()
    try:
        trace_id = adopted.trace_id
        roots = [
            s for s in svc0.tracer.spans
            if s.name == "fd_signal" and (s.trace_id or s.span_id) == trace_id
        ]
        assert roots, "the detecting node's fd_signal does not root the trace"
        assert adopted.parent_span_id in {s.span_id for s in roots}
        # node 1's receive half parents under node 0's fd_signal across the
        # process boundary (span ids are process-unique in this build)
        batches = [
            s for s in svc1.tracer.spans
            if s.name == "alert_batch" and s.trace_id == trace_id
        ]
        assert any(s.parent_id == adopted.parent_span_id for s in batches)
        for svc in (svc0, svc1):
            assert any(
                s.name == "view_change" and s.trace_id == trace_id
                for s in svc.tracer.spans
            ), "a member's view_change left the churn trace"
            assert svc._churn_ctx is None  # episode closed on install
    finally:
        h.shutdown()


def test_tracecat_merges_one_churn_across_processes():
    """The acceptance criterion verbatim: merge the per-node Chrome traces
    and find one trace id whose events -- fd_signal through view_change --
    span at least two processes."""
    h, svc0, svc1, adopted = _staggered_churn_cluster()
    try:
        trace_id = adopted.trace_id
        merged = merge_traces(
            [chrome_trace(svc0.tracer), chrome_trace(svc1.tracer)],
            labels=["node0", "node1"],
            trace_id=trace_id,
        )
        events = [
            e for e in merged["traceEvents"]
            if e.get("ph") == "X" and e.get("pid") != 1  # wall rows only
        ]
        assert events
        assert all(e["args"]["trace_id"] == trace_id for e in events)
        pids = {e["pid"] for e in events}
        assert len(pids) >= 2, "the churn trace stayed within one process"
        names_of = lambda pid: {e["name"] for e in events if e["pid"] == pid}
        pids_with_vc = [p for p in pids if "view_change" in names_of(p)]
        assert len(pids_with_vc) >= 2
        assert any("fd_signal" in names_of(p) for p in pids)
        # per-node process rows keep their labels in the merged file
        process_names = {
            e["args"]["name"] for e in merged["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "process_name"
        }
        assert {"node0/protocol", "node1/protocol"} <= process_names
    finally:
        h.shutdown()


def test_tracecat_cli_merges_files(tmp_path):
    from tools.tracecat import main as tracecat_main

    h, svc0, svc1, adopted = _staggered_churn_cluster()
    try:
        t0, t1 = tmp_path / "n0.json", tmp_path / "n1.json"
        t0.write_text(json.dumps(chrome_trace(svc0.tracer)))
        t1.write_text(json.dumps(chrome_trace(svc1.tracer)))
        out = tmp_path / "merged.json"
        rc = tracecat_main([
            str(t0), str(t1), "-o", str(out),
            "--trace-id", str(adopted.trace_id),
        ])
        assert rc == 0
        merged = json.loads(out.read_text())
        pids = {
            e["pid"] for e in merged["traceEvents"]
            if e.get("ph") == "X" and e.get("pid") != 1
        }
        assert len(pids) >= 2  # labels derive from the file stems
    finally:
        h.shutdown()


# ---------------------------------------------------------------------------
# Introspection RPC
# ---------------------------------------------------------------------------


def _fetch_status(h, probe, target):
    p = probe.send_message(target, ClusterStatusRequest(sender=probe.address))
    assert h.scheduler.run_until(p.done, timeout_ms=60_000)
    assert p.exception() is None, p.exception()
    reply = p.peek()
    assert isinstance(reply, ClusterStatusResponse)
    return reply


def test_status_rpc_members_agree_on_configuration():
    h = ClusterHarness(seed=3)
    try:
        h.create_cluster(4)
        h.wait_and_verify_agreement(4)
        probe = InProcessClient(
            Endpoint.from_parts("127.0.0.1", 9999), h.network, h.settings
        )
        replies = [_fetch_status(h, probe, ep) for ep in list(h.instances)]
        expected = h.instances[h.addr(0)].get_current_configuration_id()
        assert {r.configuration_id for r in replies} == {expected}
        assert all(r.membership_size == 4 for r in replies)
        assert all(r.sender == ep for r, ep in zip(replies, h.instances))
        # quiescent cluster: nothing tracked by the cut detector
        assert all(r.reports_tracked == 0 for r in replies)
        assert all(not r.consensus_decided for r in replies)
        for reply in replies:
            digest = dict(zip(reply.metric_names, reply.metric_values))
            assert digest.get("messages.ClusterStatusRequest", 0) >= 1
            entries = [json.loads(line) for line in reply.journal]
            assert any(e["kind"] == "status_served" for e in entries)
            assert all(e["node"] == str(reply.sender) for e in entries)
        # the RPC-free local path answers the same snapshot
        local = h.instances[h.addr(0)].get_cluster_status()
        assert local.configuration_id == expected
        assert local.membership_size == 4
    finally:
        h.shutdown()


def test_status_rpc_works_through_the_nemesis():
    plan = FaultPlan(seed=5).duplicate(0.2).reorder(0.2, max_extra_ms=40)
    h = ClusterHarness(seed=5).with_faults(plan)
    try:
        h.create_cluster(3)
        h.wait_and_verify_agreement(3)
        probe = InProcessClient(
            Endpoint.from_parts("127.0.0.1", 9999), h.network, h.settings
        )
        replies = [_fetch_status(h, probe, ep) for ep in list(h.instances)]
        assert len({r.configuration_id for r in replies}) == 1
        assert all(r.membership_size == 3 for r in replies)
    finally:
        h.shutdown()


# ---------------------------------------------------------------------------
# Golden merged trace (tools/tracecat.py output is bit-reproducible)
# ---------------------------------------------------------------------------


def _merged_golden_traces():
    """Two hand-built per-node tracers with fixed ids/timestamps: node-2's
    wall clock starts 5 s after node-1's, so the merged file exercises the
    virtual-axis wall alignment, not just the pid remap."""
    n1 = Tracer(plane="protocol", track="127.0.0.1:1234")
    n1.spans.append(Span(
        name="fd_signal", wall_start_s=2.0, wall_end_s=2.0,
        virtual_start_ms=1000, virtual_end_ms=1000,
        attrs={"subject": "127.0.0.1:1236"},
        span_id=11, parent_id=None, plane="protocol",
        track="127.0.0.1:1234", trace_id=11,
    ))
    n1.spans.append(Span(
        name="view_change", wall_start_s=2.4, wall_end_s=2.45,
        virtual_start_ms=1400, virtual_end_ms=1450, attrs={"size": 1},
        span_id=12, parent_id=11, plane="protocol",
        track="127.0.0.1:1234", trace_id=11,
    ))
    n2 = Tracer(plane="protocol", track="127.0.0.1:1235")
    n2.spans.append(Span(
        name="alert_batch", wall_start_s=7.1, wall_end_s=7.15,
        virtual_start_ms=1100, virtual_end_ms=1150,
        attrs={"origin": "127.0.0.1:1234", "alerts": 1},
        span_id=21, parent_id=11, plane="protocol",
        track="127.0.0.1:1235", trace_id=11,
    ))
    n2.spans.append(Span(
        name="view_change", wall_start_s=7.4, wall_end_s=7.46,
        virtual_start_ms=1400, virtual_end_ms=1460, attrs={"size": 1},
        span_id=22, parent_id=11, plane="protocol",
        track="127.0.0.1:1235", trace_id=11,
    ))
    return n1, n2


def test_merged_trace_matches_golden():
    import pathlib

    n1, n2 = _merged_golden_traces()
    merged = merge_traces(
        [chrome_trace(n1), chrome_trace(n2)], labels=["node1", "node2"]
    )
    golden = pathlib.Path(__file__).parent / "golden" / "merged_chrome_trace.json"
    assert merged == json.loads(golden.read_text())


def test_merged_trace_aligns_wall_rows_on_the_virtual_axis():
    n1, n2 = _merged_golden_traces()
    merged = merge_traces(
        [chrome_trace(n1), chrome_trace(n2)], labels=["node1", "node2"]
    )
    wall = [
        e for e in merged["traceEvents"]
        if e.get("ph") == "X" and e["pid"] != 1
    ]
    by_node = {}
    for e in wall:
        by_node.setdefault(e["pid"], {})[e["name"]] = e
    (a, b) = sorted(by_node.values(), key=lambda d: min(e["ts"] for e in d.values()))
    # both nodes' view_change happen at virtual 1400 ms; even though node-2's
    # wall clock starts 5 s later, the dual-emit offset puts the wall rows on
    # the shared axis and they land (to rounding) at the same instant
    assert abs(a["view_change"]["ts"] - b["view_change"]["ts"]) < 2_000
    # causal order survives the merge: fd_signal precedes the remote batch
    assert a["fd_signal"]["ts"] < b["alert_batch"]["ts"]
