"""Continuous profiling plane (ISSUE 15): per-phase device attribution,
metric history rings, and the cluster-wide telemetry scrape.

Pins the acceptance criteria layer by layer:

- attribution (profiling/phases.py): sampled shadow measurement splits the
  sim round pipeline into fd_scan / cut_detector / consensus_count /
  host_transfer, the device phases track the independently timed full step
  (>= 90% coverage at the 10k bench point, slow-marked), sampling cadence
  is 1-of-N, and the kill switch leaves the dispatch loop untouched;
- overhead discipline: the instrumented warmed decision loop stays within
  the profiling overhead budget of the raw one, and a steady-state run
  with profiling ON still compiles nothing (the bench's
  ``jit_compiles_steady == 0`` pin survives the plane);
- history rings (observability.MetricsHistory): interval gating, bounded
  downsample-on-overflow retention, wire round-trip with malformed-line
  tolerance, and export stability under concurrent child registry churn
  (the GC-finalizer absorb path);
- the scrape surface: frozen wire bytes for the extended cluster-status
  RPC (tests/golden/scrape_frames.json, both transports), old-frame
  default tolerance, scrape assembly (profiling/scrape.py), and a 3-node
  in-process cluster whose scraped responses fold into a cluster-wide
  timeseries;
- tools/perfscope.py: the render/diff CLI contract over real exporter
  output.
"""

import gc
import json
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from golden.scrape_fixtures import (
    HIERARCHY_RESPONSE,
    HISTORY_LINES,
    HLC_RESPONSE,
    SCRAPE_REQUEST,
    SCRAPE_RESPONSE,
    SLO_RESPONSE,
    TCP_SCRAPES,
)
from harness import ClusterHarness

from rapid_tpu import Endpoint, Settings
from rapid_tpu.messaging import grpc_transport as gt
from rapid_tpu.messaging.codec import HEADER, decode, encode
from rapid_tpu.messaging.inprocess import InProcessClient
from rapid_tpu.messaging.wire_schema import MSG
from rapid_tpu.observability import (
    Metrics,
    MetricsHistory,
    json_snapshot,
    prometheus_text,
)
from rapid_tpu.profiling import (
    DEVICE_PHASES,
    PhaseProfiler,
    cluster_timeseries,
    merge_by_series,
)
from rapid_tpu.profiling.scrape import node_segments, node_series
from rapid_tpu.settings import ProfilingSettings
from rapid_tpu.types import ClusterStatusRequest, ClusterStatusResponse
from tools.perfscope import diff_artifacts, extract_phases, parse_rendered

GOLDEN = json.loads(
    (Path(__file__).parent / "golden" / "scrape_frames.json").read_text()
)


# ---------------------------------------------------------------------------
# attribution: the PhaseProfiler over a real simulator
# ---------------------------------------------------------------------------


def _profiled_sim(n, seed, sample_every=1):
    from rapid_tpu.sim.driver import Simulator

    sim = Simulator(n, seed=seed, metrics=Metrics())
    sim.ready()
    prof = sim.enable_profiling(ProfilingSettings(
        enabled=True, sample_every_dispatches=sample_every,
    ))
    assert prof is not None and prof.enabled
    return sim, prof


def test_sampling_cadence_is_one_of_n():
    prof = PhaseProfiler(
        Metrics(), ProfilingSettings(enabled=True, sample_every_dispatches=4)
    )
    pattern = [prof.should_sample() for _ in range(8)]
    assert pattern == [True, False, False, False, True, False, False, False]


def test_kill_switch_disables_everything():
    prof = PhaseProfiler(Metrics(), ProfilingSettings(enabled=False))
    assert not prof.enabled
    assert not any(prof.should_sample() for _ in range(32))

    from rapid_tpu.sim.driver import Simulator

    sim = Simulator(64, seed=3, metrics=Metrics())
    assert sim.enable_profiling(ProfilingSettings(enabled=False)) is None
    assert sim._profiler is None


def test_shadow_sample_attributes_the_phase_pipeline():
    """One shadow sample yields every device phase, non-negative and on the
    same scale as the full step; the histograms land in the registry in
    exactly the shape tools/perfscope.py parses back out."""
    sim, prof = _profiled_sim(256, seed=7)
    inputs = sim._const_inputs(None)
    s = prof.sample(sim.config, sim.state, inputs, False, repeats=3)
    assert set(s) == set(DEVICE_PHASES) | {"step_ms"}
    assert all(v >= 0.0 for v in s.values())
    device_ms = sum(s[p] for p in DEVICE_PHASES)
    # the phases are differenced prefixes: they reconstruct the full step
    # up to per-prefix timer noise (clamping at zero can only push the sum
    # a noise-term above the step, never to a different scale)
    assert device_ms <= s["step_ms"] * 2.0 + 1.0

    phases, step = extract_phases(json_snapshot(sim.metrics))
    assert set(phases) >= set(DEVICE_PHASES)
    assert step is not None and step[0] >= 1
    totals = prof.attribution()
    assert set(totals) == {*DEVICE_PHASES, "host_transfer"}
    assert totals["fd_scan"] == pytest.approx(phases["fd_scan"][1])


def test_dispatch_loop_samples_and_times_host_transfer():
    """With profiling enabled the decision loop records shadow samples, the
    real decision-fetch leg, and history snapshots -- and still decides the
    identical cut."""
    sim, prof = _profiled_sim(64, seed=5, sample_every=1)
    sim.crash(np.array([3]))
    record = sim.run_until_decision(max_rounds=40)
    assert record is not None and set(record.cut) == {3}
    assert prof.samples >= 1
    totals = prof.attribution()
    assert totals["host_transfer"] > 0.0  # the fetch is real, so is its time
    assert len(prof.history) >= 1
    assert sim.metrics.get("profile.samples") == prof.samples


@pytest.mark.slow
def test_attribution_covers_device_step_at_bench_point():
    """ISSUE 15 acceptance: at the 10k-node bench point the attributed
    device phases cover >= 90% of the independently measured device step
    time. Best-of-N on both sides so scheduler jitter cannot fail a
    structurally sound attribution."""
    from rapid_tpu.profiling.phases import profile_full_step
    from rapid_tpu.runtime import jitwatch

    sim, prof = _profiled_sim(10_000, seed=11)
    inputs = sim._const_inputs(None)
    s = prof.sample(sim.config, sim.state, inputs, False, repeats=5)

    def timed_step():
        t0 = time.perf_counter()
        out = profile_full_step(sim.config, sim.state, inputs, False)
        jitwatch.drain("test.profile.step", out)
        return (time.perf_counter() - t0) * 1000.0

    step_ms = min(timed_step() for _ in range(5))
    device_ms = sum(s[p] for p in DEVICE_PHASES)
    assert device_ms >= 0.9 * step_ms, (
        f"attribution covers {device_ms / step_ms * 100:.1f}% "
        f"(device={device_ms:.3f}ms step={step_ms:.3f}ms): {s}"
    )


def test_profiling_overhead_within_budget():
    """The instrumented warmed decision loop (profiling ON, default 1-of-16
    sampling) stays within ProfilingSettings.overhead_budget_pct of the raw
    loop, plus a small absolute allowance for timer noise."""
    import sys

    from rapid_tpu.sim.driver import Simulator

    budget_pct = ProfilingSettings(enabled=True).overhead_budget_pct

    def best_of(profiled, runs=5):
        best = float("inf")
        for _ in range(runs):
            sim = Simulator(64, seed=5, metrics=Metrics())
            sim.ready()
            if profiled:
                sim.enable_profiling(ProfilingSettings(enabled=True))
            sim.crash(np.array([3]))
            t0 = time.perf_counter()
            record = sim.run_until_decision(max_rounds=40)
            best = min(best, time.perf_counter() - t0)
            assert record is not None
        return best

    best_of(True, runs=1)  # jit warmup (shadow prefixes included)
    plain = best_of(False)
    instrumented = best_of(True)
    slack = 0.25 if sys.gettrace() is not None else 0.05
    assert instrumented <= plain * (1.0 + budget_pct / 100.0) + slack, (
        f"profiling overhead: instrumented={instrumented * 1e3:.1f}ms "
        f"plain={plain * 1e3:.1f}ms budget={budget_pct}%"
    )


def test_profiling_on_keeps_steady_state_compiles_zero():
    """The bench pin extended to the profiling plane: after one warmup run,
    an identically shaped profiled run compiles NOTHING -- the shadow
    prefixes were compiled at enable time, never on the steady path."""
    from rapid_tpu.runtime import jitwatch
    from rapid_tpu.sim.driver import Simulator

    def run():
        sim = Simulator(64, seed=5, metrics=Metrics())
        sim.ready()
        sim.enable_profiling(ProfilingSettings(
            enabled=True, sample_every_dispatches=1,
        ))
        sim.crash(np.array([3]))
        record = sim.run_until_decision(max_rounds=40)
        assert record is not None

    run()  # warmup: production loop + shadow prefixes compile here
    js0 = jitwatch.stats()
    run()  # identical shapes: the steady state
    js1 = jitwatch.stats()
    assert js1["compiles"] - js0["compiles"] == 0, (
        f"profiled steady-state run compiled "
        f"{js1['compiles'] - js0['compiles']} times"
    )


# ---------------------------------------------------------------------------
# metric history rings
# ---------------------------------------------------------------------------


def test_history_interval_gating_and_series():
    m = Metrics()
    h = MetricsHistory(m, interval_s=1.0, capacity=16)
    m.incr("rounds", 3)
    assert h.maybe_snapshot(10.0)
    m.incr("rounds", 2)
    assert not h.maybe_snapshot(10.5)  # inside the interval
    assert h.maybe_snapshot(11.0)
    assert len(h) == 2
    assert h.series("rounds") == [(10.0, 3.0), (11.0, 5.0)]
    m.observe("profile.step_ms", 2.5, plane="sim")
    h.snapshot(12.0)
    assert h.series("profile.step_ms{plane=sim}") == [(12.0, 1.0)]  # count


def test_history_overflow_downsamples_old_keeps_recent():
    """The overflow edge: a ring that snapshots forever stays within
    [3/4*capacity, capacity], keeps snapshots ordered, and never loses the
    newest entries to decimation (only the oldest half coarsens)."""
    m = Metrics()
    h = MetricsHistory(m, interval_s=0.0, capacity=8)
    for t in range(200):
        m.incr("rounds")
        h.snapshot(float(t))
    assert 6 <= len(h) <= 8
    ts = [snap["ts_s"] for snap in h.entries()]
    assert ts == sorted(ts) and len(set(ts)) == len(ts)
    assert ts[-1] == 199.0 and ts[-2] == 198.0  # recent half: full resolution
    values = [v for _, v in h.series("rounds")]
    assert values == sorted(values)  # counters survive decimation monotone


def test_history_wire_roundtrip_skips_malformed_lines():
    m = Metrics()
    h = MetricsHistory(m, interval_s=0.0, capacity=8)
    m.incr("rounds")
    h.snapshot(1.0)
    m.incr("rounds")
    h.snapshot(2.0)
    lines = h.to_wire()
    assert len(lines) == 2
    assert h.to_wire(1) == lines[-1:]
    back = MetricsHistory.from_wire(lines)
    assert [s["ts_s"] for s in back] == [1.0, 2.0]
    assert back[1]["counters"]["rounds"] == 2
    # a truncated scrape never breaks assembly
    mangled = (lines[0], "{not json", lines[1][: len(lines[1]) // 2])
    assert [s["ts_s"] for s in MetricsHistory.from_wire(mangled)] == [1.0]


def test_exports_survive_concurrent_child_churn_and_absorb():
    """Satellite (c): churn child registries (attach, record, die -> the GC
    finalizer queues an absorb) while another thread exports and snapshots
    the parent. No export may raise, and when the dust settles the absorbed
    counters are conserved exactly."""
    parent = Metrics()
    h = MetricsHistory(parent, interval_s=0.0, capacity=32)
    children = 150
    errors = []

    def churn():
        try:
            for i in range(children):
                child = Metrics(parent=parent, plane="churn")
                child.incr("rounds")
                child.observe("profile.step_ms", 0.5, plane="sim")
                del child
                if i % 10 == 0:
                    gc.collect()
        except Exception as exc:  # pragma: no cover - the assertion target
            errors.append(exc)

    worker = threading.Thread(target=churn)
    worker.start()
    try:
        while worker.is_alive():
            prometheus_text(parent)
            json_snapshot(parent)
            h.snapshot(time.time())
    finally:
        worker.join()
    assert not errors, errors
    gc.collect()
    assert parent.get("rounds") == children  # every absorb folded, once
    final = h.snapshot(time.time())
    assert final["counters"]["rounds{plane=churn}"] == children


# ---------------------------------------------------------------------------
# the scrape surface on the wire: frozen bytes + old-frame tolerance
# ---------------------------------------------------------------------------


def test_scrape_frame_bytes_golden():
    """Native-codec scrape frames serialize byte-for-byte to the committed
    vectors and the committed bytes decode back to identical values."""
    assert set(GOLDEN["tcp_frames"]) == set(TCP_SCRAPES)
    for name, (request_no, msg) in TCP_SCRAPES.items():
        entry = GOLDEN["tcp_frames"][name]
        assert entry["request_no"] == request_no, name
        body = encode(request_no, msg)
        assert body.hex() == entry["body_hex"], name
        framed = HEADER.pack(len(body)) + body
        assert framed.hex() == entry["framed_hex"], name
        got_no, got = decode(bytes.fromhex(entry["body_hex"]))
        assert (got_no, got) == (request_no, msg), name


def test_scrape_grpc_bytes_golden():
    """The gRPC scrape extension serializes deterministically to the
    committed bytes (includeHistory field 2, history field 33) and parses
    back identical through the programmatic schema."""
    wire = gt.to_wire_request(SCRAPE_REQUEST).SerializeToString(
        deterministic=True
    )
    assert wire.hex() == GOLDEN["grpc"]["ClusterStatusRequest"]
    parsed = gt.from_wire_request(MSG["RapidRequest"].FromString(wire))
    assert parsed == SCRAPE_REQUEST

    wire = gt.to_wire_response(SCRAPE_RESPONSE).SerializeToString(
        deterministic=True
    )
    assert wire.hex() == GOLDEN["grpc"]["ClusterStatusResponse"]
    parsed = gt.from_wire_response(MSG["RapidResponse"].FromString(wire))
    assert parsed == SCRAPE_RESPONSE
    assert parsed.history == HISTORY_LINES

    # the SLO alert digest (fields 37-40) rides the same response
    wire = gt.to_wire_response(SLO_RESPONSE).SerializeToString(
        deterministic=True
    )
    assert wire.hex() == GOLDEN["grpc"]["ClusterStatusResponse_slo"]
    parsed = gt.from_wire_response(MSG["RapidResponse"].FromString(wire))
    assert parsed == SLO_RESPONSE
    assert parsed.slo_burn_milli == (150, 42100)
    assert parsed.slo_firing == (0, 1)

    # the forensics digest (journal accounting + HLC, fields 41-45)
    # rides the same response
    wire = gt.to_wire_response(HLC_RESPONSE).SerializeToString(
        deterministic=True
    )
    assert wire.hex() == GOLDEN["grpc"]["ClusterStatusResponse_hlc"]
    parsed = gt.from_wire_response(MSG["RapidResponse"].FromString(wire))
    assert parsed == HLC_RESPONSE
    assert parsed.hlc_incarnation == 2
    assert parsed.journal_dropped == 6

    # the hierarchy digest (cell coordinates + composed global view,
    # fields 46-53) rides the same response
    wire = gt.to_wire_response(HIERARCHY_RESPONSE).SerializeToString(
        deterministic=True
    )
    assert wire.hex() == GOLDEN["grpc"]["ClusterStatusResponse_hierarchy"]
    parsed = gt.from_wire_response(MSG["RapidResponse"].FromString(wire))
    assert parsed == HIERARCHY_RESPONSE
    assert parsed.cell_id == 1
    assert parsed.global_cells == (0, 1)


def test_pre_profiling_frames_parse_to_defaults():
    """Rolling upgrade both ways: an old peer's frame (no scrape fields)
    parses with the defaults, and a scrape-bearing frame parsed by the
    pre-profiling schema subset keeps everything it knows."""
    old_req = ClusterStatusRequest(sender=SCRAPE_REQUEST.sender)
    assert old_req.include_history == 0
    assert decode(encode(3, old_req)) == (3, old_req)

    old_resp = ClusterStatusResponse(
        sender=SCRAPE_RESPONSE.sender, configuration_id=1, membership_size=2,
    )
    wire = gt.to_wire_response(old_resp).SerializeToString(deterministic=True)
    back = gt.from_wire_response(MSG["RapidResponse"].FromString(wire))
    assert back == old_resp and back.history == ()
    # pre-SLO peers' frames fill the alert digest with its empty defaults
    assert back.slo_names == () and back.slo_firing == ()
    # pre-forensics peers' frames fill the HLC digest with zeros
    assert back.hlc_physical_ms == 0 and back.hlc_incarnation == 0


# ---------------------------------------------------------------------------
# scrape assembly
# ---------------------------------------------------------------------------


def test_node_series_from_wire_lines():
    series = node_series(HISTORY_LINES)
    assert series["rounds"] == [(12.0, 3.0), (13.0, 5.0)]
    hist = "profile.phase_ms{phase=fd_scan,plane=sim}"
    assert series[f"{hist}.count"] == [(12.0, 3.0), (13.0, 5.0)]
    assert series[f"{hist}.sum"] == [(12.0, 1.5), (13.0, 2.25)]
    gauge = "msg.queue_depth{peer=10.9.1.3:7103}"
    assert series[gauge] == [(12.0, 128.0)]


def test_cluster_timeseries_merges_and_prefers_larger_scrape():
    plain = ClusterStatusResponse(
        sender=SCRAPE_REQUEST.sender, configuration_id=1, membership_size=3,
    )
    partial = ClusterStatusResponse(
        sender=SCRAPE_RESPONSE.sender, configuration_id=1, membership_size=3,
        history=HISTORY_LINES[:1],
    )
    cluster = cluster_timeseries([plain, partial, SCRAPE_RESPONSE])
    assert set(cluster) == {str(plain.sender), str(SCRAPE_RESPONSE.sender)}
    assert cluster[str(plain.sender)] == {}  # old peer: present, empty
    # the duplicate node kept the larger scrape (both snapshots)
    assert cluster[str(SCRAPE_RESPONSE.sender)]["rounds"] == [
        (12.0, 3.0), (13.0, 5.0),
    ]
    merged = merge_by_series(cluster)
    assert merged["rounds"] == {
        str(SCRAPE_RESPONSE.sender): [(12.0, 3.0), (13.0, 5.0)],
    }


# ---------------------------------------------------------------------------
# 3-node cluster scrape -> cluster-wide timeseries (pinned integration)
# ---------------------------------------------------------------------------


def _scrape(h, probe, target, include_history):
    p = probe.send_message(target, ClusterStatusRequest(
        sender=probe.address, include_history=include_history,
    ))
    assert h.scheduler.run_until(p.done, timeout_ms=60_000)
    assert p.exception() is None, p.exception()
    reply = p.peek()
    assert isinstance(reply, ClusterStatusResponse)
    return reply


def test_three_node_cluster_scrape_assembles_cluster_timeseries():
    """ISSUE 15 acceptance: with profiling enabled, any scraper folds the
    members' status responses into a cluster-wide timeseries -- three
    nodes, each with a multi-point profile.history_snapshots series on the
    deterministic virtual clock."""
    settings = Settings(profiling=ProfilingSettings(
        enabled=True, history_interval_ms=200, history_capacity=16,
    ))
    h = ClusterHarness(seed=15, settings=settings)
    try:
        h.create_cluster(3)
        h.wait_and_verify_agreement(3)
        probe = InProcessClient(
            Endpoint.from_parts("127.0.0.1", 9999), h.network, h.settings
        )
        members = list(h.instances)
        # every status call ticks the ring; include_history=0 returns none
        for _ in range(2):
            for ep in members:
                tick = _scrape(h, probe, ep, 0)
                assert tick.history == ()
            h.scheduler.run_until(lambda: False, timeout_ms=500)
        replies = [_scrape(h, probe, ep, 8) for ep in members]
        assert all(len(r.history) >= 2 for r in replies)

        cluster = cluster_timeseries(replies)
        assert set(cluster) == {str(ep) for ep in members}
        for node, series in cluster.items():
            by_base = {}
            for name, points in series.items():
                by_base.setdefault(parse_rendered(name)[0], []).extend(points)
            snaps = sorted(by_base["profile.history_snapshots"])
            assert len(snaps) >= 2, node
            counts = [v for _, v in snaps]
            assert counts == sorted(counts), node  # monotone on virtual time
        # the transposed comparison view spans every member
        merged = merge_by_series(cluster)
        spanning = {
            parse_rendered(name)[0]: set(nodes)
            for name, nodes in merged.items()
        }
        assert any(
            base == "profile.history_snapshots" for base in spanning
        )
    finally:
        h.shutdown()


def test_node_series_does_not_interleave_restarted_incarnations():
    """A virtual-clock member restarts at t=0: the new incarnation's
    timestamps sort BELOW the old ones. The per-incarnation seq stamp
    keeps the assembled series in incarnation order where the old global
    ts sort zig-zagged the two incarnations into one broken series."""
    lines = (
        '{"counters": {"rounds": 10.0}, "gauges": {}, "histograms": {}, '
        '"seq": 1, "ts_s": 50.0}',
        '{"counters": {"rounds": 20.0}, "gauges": {}, "histograms": {}, '
        '"seq": 2, "ts_s": 60.0}',
        # restart: the virtual clock AND the seq stamp both start over
        '{"counters": {"rounds": 1.0}, "gauges": {}, "histograms": {}, '
        '"seq": 1, "ts_s": 5.0}',
        '{"counters": {"rounds": 2.0}, "gauges": {}, "histograms": {}, '
        '"seq": 2, "ts_s": 15.0}',
    )
    segments = node_segments(lines)
    assert [seg["rounds"] for seg in segments] == [
        [(50.0, 10.0), (60.0, 20.0)],
        [(5.0, 1.0), (15.0, 2.0)],
    ]
    series = node_series(lines)
    assert series["rounds"] == [
        (50.0, 10.0), (60.0, 20.0), (5.0, 1.0), (15.0, 2.0),
    ]
    # old peers' seq-less lines still split on the ts regression alone
    legacy = tuple(
        json.dumps(
            {k: v for k, v in json.loads(line).items() if k != "seq"},
            sort_keys=True,
        )
        for line in lines
    )
    assert len(node_segments(legacy)) == 2


def test_scrape_split_across_restarted_cluster_member():
    """A scraper accumulating one member's history lines across that
    member's restart: the fresh ring restarts the seq stamp at 1, so
    node_segments splits at the incarnation boundary and node_series keeps
    the concatenation in incarnation order (the restarted node's counters
    visibly begin again instead of merging into a zig-zag)."""
    settings = Settings(profiling=ProfilingSettings(
        enabled=True, history_interval_ms=200, history_capacity=16,
    ))
    h = ClusterHarness(seed=17, settings=settings)
    try:
        h.create_cluster(3)
        h.wait_and_verify_agreement(3)
        probe = InProcessClient(
            Endpoint.from_parts("127.0.0.1", 9998), h.network, h.settings
        )
        target = h.addr(2)
        for _ in range(3):
            _scrape(h, probe, target, 0)  # status calls tick the ring
            h.scheduler.run_until(lambda: False, timeout_ms=500)
        before = _scrape(h, probe, target, 8).history
        assert len(before) >= 2

        h.fail_nodes([target])
        h.wait_and_verify_agreement(2)  # the FD evicts the dead seat
        h.blacklist.discard(target)
        h.join(2, seed_index=0)  # same endpoint, fresh incarnation
        h.wait_and_verify_agreement(3)
        for _ in range(3):
            _scrape(h, probe, target, 0)
            h.scheduler.run_until(lambda: False, timeout_ms=500)
        after = _scrape(h, probe, target, 8).history
        assert len(after) >= 2

        carriage = before + after  # the scraper's accumulated lines
        segments = node_segments(carriage)
        assert len(segments) == 2  # one per incarnation

        def snap_points(seg):
            key = next(
                k for k in seg
                if parse_rendered(k)[0] == "profile.history_snapshots"
            )
            return key, seg[key]

        key, first = snap_points(segments[0])
        _, second = snap_points(segments[1])
        for points in (first, second):
            counts = [v for _, v in points]
            assert counts == sorted(counts)  # monotone inside incarnation
        # the ring really restarted: the counter began again
        assert second[0][1] <= first[-1][1]
        # and the flat series preserves incarnation order end to end
        assert node_series(carriage)[key] == first + second
    finally:
        h.shutdown()


def test_scrape_without_profiling_returns_no_history():
    h = ClusterHarness(seed=16)  # default settings: profiling disabled
    try:
        h.create_cluster(2)
        h.wait_and_verify_agreement(2)
        probe = InProcessClient(
            Endpoint.from_parts("127.0.0.1", 9999), h.network, h.settings
        )
        reply = _scrape(h, probe, h.addr(0), 8)
        assert reply.history == ()
        assert reply.membership_size == 2
    finally:
        h.shutdown()


# ---------------------------------------------------------------------------
# tools/perfscope.py contract
# ---------------------------------------------------------------------------


def test_perfscope_renders_real_exporter_output(tmp_path, capsys):
    """End to end: profile a real simulator, dump json_snapshot, and the
    CLI renders every phase plus the coverage line and writes a loadable
    Chrome trace."""
    from tools.perfscope import main as perfscope

    sim, prof = _profiled_sim(128, seed=9)
    inputs = sim._const_inputs(None)
    prof.sample(sim.config, sim.state, inputs, False, repeats=2)
    prof.record_host_transfer(0.05)
    artifact = tmp_path / "metrics.json"
    artifact.write_text(json.dumps(json_snapshot(sim.metrics)))
    trace = tmp_path / "trace.json"

    rc = perfscope(["render", str(artifact), "--trace-out", str(trace)])
    out = capsys.readouterr().out
    assert rc == 0
    for phase in (*DEVICE_PHASES, "host_transfer"):
        assert phase in out
    assert "device step" in out
    events = json.loads(trace.read_text())["traceEvents"]
    assert [e["name"] for e in events] == [
        "fd_scan", "cut_detector", "consensus_count", "host_transfer",
    ]
    assert all(e["ph"] == "X" for e in events)


def test_perfscope_diff_flags_regressions():
    old = {"metric": "m", "value": 100.0, "backend": "cpu",
           "sweep": [{"n": 64, "warmed_wall_ms": 10.0,
                      "jit_compiles_steady": 0}]}
    new = dict(old, value=125.0,
               sweep=[{"n": 64, "warmed_wall_ms": 10.2,
                       "jit_compiles_steady": 2}])
    text, regressions = diff_artifacts(old, new, threshold=0.10)
    assert "headline: 100.0 -> 125.0" in text
    assert any("headline" in r for r in regressions)
    assert any("jit_compiles_steady" in r for r in regressions)
    _, clean = diff_artifacts(old, dict(old, value=104.0), threshold=0.10)
    assert clean == []


def _check_artifact() -> dict:
    """A healthy bench artifact carrying every DIMENSION_BUDGETS path."""
    return {
        "metric": "decision_wall_ms", "value": 1200.0,
        "serving_qps": {
            "steady": {"p99_ms": 4.0},
            "lost_acked_writes": 0,
            "throughput_qps": 550.0,
            "slo": {
                "serving.availability": {
                    "availability": 1.0, "goodput_ratio": 1.0,
                },
                "serving.latency": {
                    "alerts": {"fast": {"firing": False}},
                },
            },
        },
        "messaging_throughput": {
            "broadcast_storm": {"messages_per_s": 9000.0},
        },
        "gray_detection_ms": {
            "gray_slow_node": {"speedup": 4.2},
            "gray_flapping": {"speedup": 2.4},
        },
        "hierarchy_scale": {
            "member_ceiling_ratio": 10.0,
            "agreement_virtual_ms": 2200.0,
            "hierarchical": {"parent_rounds": 3},
        },
    }


def test_perfscope_check_budgets_pure():
    """check_budgets gates the headline plus every dimension path the
    artifact carries, skipping absent dimensions instead of failing."""
    from tools.perfscope import DIMENSION_BUDGETS, check_budgets

    doc = _check_artifact()
    lines, breaches = check_budgets(doc)
    assert breaches == []
    # every budget row found its leaf: headline + all table rows reported
    assert len(lines) == 1 + len(DIMENSION_BUDGETS)
    assert all("within" in line for line in lines)

    # one breach per broken leaf, each naming its dimension
    doc["serving_qps"]["steady"]["p99_ms"] = 80.0
    doc["serving_qps"]["slo"]["serving.latency"]["alerts"]["fast"][
        "firing"] = True
    doc["gray_detection_ms"]["gray_flapping"]["speedup"] = 1.1
    _, breaches = check_budgets(doc)
    assert len(breaches) == 3
    assert {b.split(":")[0] for b in breaches} == {"serving", "slo", "gray"}

    # headline over budget is a breach too
    _, breaches = check_budgets(_check_artifact(), budget_ms=1000.0)
    assert breaches == ["headline 1200.0 ms > 1000 ms"]

    # partial artifact (dimension never ran): its rows are skipped
    partial = {"metric": "m", "value": 100.0}
    lines, breaches = check_budgets(partial)
    assert breaches == [] and len(lines) == 1


def test_perfscope_check_cli_exit_codes(tmp_path, capsys):
    """CLI contract: rc 0 within budgets, rc 3 on any dimension breach
    (with a BUDGET BREACH line on stderr), rc 2 when the artifact has no
    headline value at all."""
    from tools.perfscope import main as perfscope

    good = tmp_path / "good.json"
    good.write_text(json.dumps(_check_artifact()))
    assert perfscope(["check", str(good)]) == 0
    out = capsys.readouterr().out
    assert "headline" in out and "serving_qps.slo" in out

    bad_doc = _check_artifact()
    bad_doc["serving_qps"]["lost_acked_writes"] = 3
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(bad_doc))
    assert perfscope(["check", str(bad)]) == 3
    err = capsys.readouterr().err
    assert "BUDGET BREACH" in err and "lost_acked_writes" in err

    outage = tmp_path / "outage.json"
    outage.write_text(json.dumps({"metric": "m", "error": "boom"}))
    assert perfscope(["check", str(outage)]) == 2
