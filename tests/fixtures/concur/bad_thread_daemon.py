"""thread-daemon: a non-daemon thread keeps the process alive at exit."""
import threading


def start_worker(fn) -> threading.Thread:
    worker = threading.Thread(target=fn, name="worker")
    worker.start()
    return worker
