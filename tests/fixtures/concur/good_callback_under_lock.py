"""callback-under-lock corrected: snapshot under the lock, call after."""
import threading


class Publisher:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._subscribers = []

    def publish(self, event) -> None:
        with self._lock:
            snapshot = list(self._subscribers)
        for callback in snapshot:
            callback(event)
