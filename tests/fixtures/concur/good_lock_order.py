"""lock-order corrected: both paths honor the same A-before-B hierarchy."""
import threading

lock_a = threading.Lock()
lock_b = threading.Lock()


def transfer_forward() -> None:
    with lock_a:
        with lock_b:
            pass


def transfer_backward() -> None:
    with lock_a:
        with lock_b:
            pass
