"""lock-order: A->B in one path, B->A in another = deadlock cycle."""
import threading

lock_a = threading.Lock()
lock_b = threading.Lock()


def transfer_forward() -> None:
    with lock_a:
        with lock_b:
            pass


def transfer_backward() -> None:
    with lock_b:
        with lock_a:
            pass
