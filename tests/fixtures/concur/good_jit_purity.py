"""jit-purity corrected: timestamps come in as traced arguments."""
import jax
import jax.numpy as jnp


@jax.jit
def stamped_sum(x, started):
    return jnp.sum(x) + started
