"""jit-purity: wall-clock read + print inside a jitted function run once at
trace time and never again -- the timestamp is baked into the graph."""
import time

import jax
import jax.numpy as jnp


@jax.jit
def stamped_sum(x):
    started = time.time()
    print("tracing", started)
    return jnp.sum(x) + started
