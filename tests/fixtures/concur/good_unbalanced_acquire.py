"""unbalanced-acquire corrected: release lives in a finally block.
(A `with` statement is better still; this pins the minimal correction.)"""
import threading

state_lock = threading.Lock()
state = []


def update(item) -> None:
    state_lock.acquire()
    try:
        state.append(item)
    finally:
        state_lock.release()
