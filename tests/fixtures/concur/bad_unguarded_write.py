"""unguarded-write: a counter bumped from a thread AND public callers,
with a lock present but not actually taken around the writes."""
import threading


class Collector:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._count = 0
        self._worker = threading.Thread(target=self._drain, daemon=True)

    def _drain(self) -> None:
        self._count += 1

    def add(self, n: int) -> None:
        self._count += n
