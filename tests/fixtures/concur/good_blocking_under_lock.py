"""blocking-under-lock corrected: decide under the lock, block outside."""
import threading
import time


class Throttle:
    def __init__(self) -> None:
        self._lock = threading.Lock()

    def pace(self) -> None:
        with self._lock:
            delay = 0.1
        time.sleep(delay)
