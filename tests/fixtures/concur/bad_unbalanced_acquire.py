"""unbalanced-acquire: manual acquire with the release outside a finally --
any exception between them leaks the lock forever."""
import threading

state_lock = threading.Lock()
state = []


def update(item) -> None:
    state_lock.acquire()
    state.append(item)
    state_lock.release()
