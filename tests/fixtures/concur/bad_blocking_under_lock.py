"""blocking-under-lock: sleeping while every other acquirer stalls."""
import threading
import time


class Throttle:
    def __init__(self) -> None:
        self._lock = threading.Lock()

    def pace(self) -> None:
        with self._lock:
            time.sleep(0.1)
