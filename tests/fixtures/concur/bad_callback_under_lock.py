"""callback-under-lock: user callbacks invoked while holding the lock can
re-enter this object (or block) and deadlock every other caller."""
import threading


class Publisher:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._subscribers = []

    def publish(self, event) -> None:
        with self._lock:
            for callback in self._subscribers:
                callback(event)
