"""unguarded-write (declared-guard variant): the attribute promises
'# guarded-by: _lock' but one write path skips the lock."""
import threading


class Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries = {}  # guarded-by: _lock
        self._worker = threading.Thread(target=self._sweep, daemon=True)

    def _sweep(self) -> None:
        with self._lock:
            self._entries.clear()

    def put(self, key: str, value: str) -> None:
        self._entries[key] = value
