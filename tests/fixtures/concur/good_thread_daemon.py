"""thread-daemon corrected: daemon=True so shutdown never hangs on it."""
import threading


def start_worker(fn) -> threading.Thread:
    worker = threading.Thread(target=fn, name="worker", daemon=True)
    worker.start()
    return worker
