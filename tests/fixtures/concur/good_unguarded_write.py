"""unguarded-write corrected: every write holds the declared guard."""
import threading


class Collector:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._count = 0  # guarded-by: _lock
        self._worker = threading.Thread(target=self._drain, daemon=True)

    def _drain(self) -> None:
        with self._lock:
            self._count += 1

    def add(self, n: int) -> None:
        with self._lock:
            self._count += n
