"""dtype-discipline corrected: every construction pins its dtype and the
narrow fields widen only through an explicit, audited .astype()."""
import jax.numpy as jnp


def build(n):
    hist = jnp.zeros((n, 8), dtype=jnp.uint8)
    ticks = jnp.arange(n, dtype=jnp.int32)
    return hist, ticks


def decay(state):
    fd_fail = state.fd_fail.astype(jnp.float32) * 0.5
    rate = state.fd_hist.astype(jnp.float32) / state.rounds
    return fd_fail, rate
