"""dtype-discipline: constructions without an explicit dtype depend on the
x64 flag and weak-type promotion (a silent compile-cache split); float
arithmetic and true division on the pinned narrow state fields silently
widen them."""
import jax.numpy as jnp


def build(n):
    hist = jnp.zeros((n, 8))
    ticks = jnp.arange(n)
    return hist, ticks


def decay(state):
    fd_fail = state.fd_fail * 0.5
    rate = state.fd_hist / state.rounds
    return fd_fail, rate
