"""donation-hygiene corrected: the driver-owned carried state is donated
(dead the moment the call returns); the differential path keeps the plain
entry and declares why the input must stay alive."""
from rapid_tpu.runtime.jitwatch import make_jit


def _advance(state, inputs):
    return state + inputs


advance = make_jit("fixture.advance", _advance, donate_argnums=(0,))
advance_shared = make_jit("fixture.advance.shared", _advance)


def drive(state, inputs):
    for _ in range(8):
        state = advance(state, inputs)
    return state


def replay(state, inputs):
    # differential readers still hold the input  # devlint: no-donate
    state = advance_shared(state, inputs)
    return state
