"""recompile-hazard corrected: every jit goes through the make_jit seam,
the per-key wrapper is cached (and tagged), the bounded static is tagged,
and the loop dispatches a fixed static value."""
import jax.numpy as jnp

from rapid_tpu.runtime.jitwatch import make_jit

_CACHE = {}


def cached_wrapper(key):
    if key not in _CACHE:
        _CACHE[key] = make_jit("fixture.step", lambda v: v * 2)  # devlint: jit-cached
    return _CACHE[key]


def _scan(x, rounds):
    return x * rounds


# rounds is drawn from a bounded set  # devlint: static-shape
scan = make_jit("fixture.scan", _scan, static_argnums=(1,))


def drive(x):
    out = []
    for _ in range(8):
        out.append(scan(x, 8))
    return out
