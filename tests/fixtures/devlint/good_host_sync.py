"""host-sync corrected: fetches go through the audited jitwatch seam (or
are declared sync points), and the jitted body selects with jnp.where."""
import jax.numpy as jnp
import numpy as np

from rapid_tpu.runtime import jitwatch
from rapid_tpu.runtime.jitwatch import make_jit


def decide(state):
    if int(np.asarray(jitwatch.fetch("fixture.round", state.round_no))) > 3:
        return jitwatch.fetch("fixture.votes", state.votes)
    # snapshot cached once per rebuild  # devlint: sync-point
    return np.asarray(state.votes)


def _step(x, flag):
    return jnp.where(flag, x + 1, x)


step = make_jit("fixture.step", _step)
