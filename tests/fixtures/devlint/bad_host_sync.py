"""host-sync: int()/.item()/np.asarray on device-resident state and a raw
jax.device_get are blocking, un-audited device->host round trips; the
jitted body branches in python on a traced parameter."""
import jax
import numpy as np

from rapid_tpu.runtime.jitwatch import make_jit


def decide(state):
    if int(state.round_no) > 3:
        return np.asarray(state.votes)
    total = state.total.item()
    return jax.device_get(state.votes), total


def _step(x, flag):
    if flag:
        return x + 1
    return x


step = make_jit("fixture.step", _step)
