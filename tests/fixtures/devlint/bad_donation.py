"""donation-hygiene: the carried state is threaded through a jitted update
with no donate_argnums -- the pre-call buffers stay live until the call
returns, doubling peak memory at state scale on every dispatch."""
from rapid_tpu.runtime.jitwatch import make_jit


def _advance(state, inputs):
    return state + inputs


advance = make_jit("fixture.advance", _advance)


def drive(state, inputs):
    for _ in range(8):
        state = advance(state, inputs)
    return state
