"""recompile-hazard: raw jax.jit bypasses the make_jit seam (its compiles
are invisible to jitwatch); a jit wrapper minted inside a function body is a
fresh executable per call; a shape-like static parameter mints one
executable per distinct value; the loop feeds its index into a static
slot."""
import jax
import jax.numpy as jnp

from rapid_tpu.runtime.jitwatch import make_jit


@jax.jit
def raw_step(x):
    return x + jnp.int32(1)


def per_call_wrapper(x):
    step = make_jit("fixture.step", lambda v: v * 2)
    return step(x)


def _scan(x, rounds):
    return x * rounds


scan = make_jit("fixture.scan", _scan, static_argnums=(1,))


def drive(x):
    out = []
    for i in range(8):
        out.append(scan(x, i))
    return out
