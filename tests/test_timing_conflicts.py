"""Timing-induced proposal conflicts: the paper's Fig.-11 regime.

Nothing is dropped in these scenarios -- the only fault beyond the crashes
is heterogeneous broadcast *latency* (SimConfig.max_delivery_delay +
Simulator.delay_broadcasts). With staggered FD phases (rounds_per_interval >
1), a victim's K observers fire alerts spread over sub-rounds; delivery
classes that hear part of the stream a few sub-rounds late cross H at
different times holding different report snapshots, and propose *different*
cuts -- conflicting proposals arising purely from timing, exactly the
conflict source the paper measures in Fig. 11 (atc-2018 §7) and the reason
Fast Paxos needs its classic fallback.

The scenario definition (run_trial) lives in
experiments/fig11_conflict_sweep.py -- the script that reproduces the
BASELINE.md table -- so the published numbers and this regression can never
desynchronize. This file pins the regime's endpoints on a smaller grid.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from experiments.fig11_conflict_sweep import drive_to_convergence, run_trial

TRIALS = [(seed, victims) for seed in range(3) for victims in ([5, 40], [11, 52])]


def test_no_conflicts_without_latency_heterogeneity():
    """Uniform timing never diverges: same stream, same crossings, one
    proposal, fast-path decision."""
    for seed, victims in TRIALS:
        conflict, rec, _ = run_trial(seed, victims, skew=0)
        assert not conflict
        assert rec is not None and not rec.via_classic_round
        assert sorted(rec.cut) == sorted(victims)


def test_latency_heterogeneity_induces_conflicting_proposals():
    """With a 9-sub-round skew (under one FD interval), every trial makes the
    two delivery classes cross H on different snapshots and propose different
    cuts; the 50/50 vote split blocks the 3/4 quorum."""
    for seed, victims in TRIALS:
        conflict, rec, _ = run_trial(seed, victims, skew=9)
        assert conflict, f"no divergence for seed={seed} victims={victims}"
        assert rec is None, "conflicting 32/32 split must stall the fast round"


def test_conflicts_resolve_through_classic_fallback():
    """The fallback converges on every timing conflict: the coordinator rule
    picks one of the proposed cuts, and any residual victim is removed by a
    follow-up view change -- final membership is exact."""
    for seed, victims in TRIALS:
        # first observe the stalled conflict, then enable the fallback on
        # the same simulator (the view change consumes the announcement
        # snapshot, so the conflict must be captured before the decision)
        conflict, stalled, sim = run_trial(seed, victims, skew=9, fallback=None)
        assert conflict and stalled is None
        rec = sim.run_until_decision(
            max_rounds=100, batch=40, classic_fallback_after_rounds=20
        )
        assert rec is not None and rec.via_classic_round
        assert set(rec.cut) <= set(victims)  # a proposed value, never invented
        drive_to_convergence(sim, 62)
        assert not sim.active[np.array(victims)].any()


def test_conflict_rate_grows_with_stagger():
    """The experiment behind the BASELINE.md row: conflict probability is
    monotone in the latency skew (0 at skew 0, 1 at skew 9 for this grid)."""
    rates = {}
    for skew in (0, 5, 9):
        conflicts = 0
        for seed, victims in TRIALS:
            conflict, _, _ = run_trial(seed, victims, skew=skew)
            conflicts += conflict
        rates[skew] = conflicts / len(TRIALS)
    assert rates[0] == 0.0
    assert rates[0] <= rates[5] <= rates[9]
    assert rates[9] == 1.0
