// Concurrency stress harness for the framed-TCP reactor, built under
// ThreadSanitizer / AddressSanitizer (make stress-tsan / stress-asan).
//
// The reference's race-detection story is static-only (error-prone,
// findbugs, @GuardedBy -- SURVEY.md section 5.2); the native reactor gets a
// dynamic one: this harness exercises every cross-thread interaction the
// contract in rapid_io.cpp promises -- concurrent connects, concurrent
// senders on shared connections, an echoing poller, mid-traffic client
// disconnects, and shutdown racing in-flight sends -- and the sanitizer
// build fails on any data race / use-after-free the interleavings expose
// (notably the close-vs-send fd-reuse races the implementation guards with
// the open-flag + shutdown-before-close-under-write_mu dance).
//
// Exit code 0 = all assertions held and the sanitizer stayed quiet.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <thread>
#include <vector>

extern "C" {
int64_t rapid_io_server_create(const char* host, int port);
int rapid_io_server_port(int64_t h);
int rapid_io_server_poll(int64_t h, int64_t* conn_id, uint8_t* buf,
                         int64_t buf_cap, int64_t* len, int timeout_ms);
int rapid_io_server_send(int64_t h, int64_t conn_id, const uint8_t* data,
                         int64_t len);
void rapid_io_server_shutdown(int64_t h);
}

namespace {

constexpr int kClients = 8;
constexpr int kFramesPerClient = 200;
constexpr int kPollers = 3;

std::atomic<int64_t> g_frames_seen{0};
std::atomic<int64_t> g_echoes_received{0};
std::atomic<bool> g_stop{false};

int connect_to(int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
  if (connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    perror("connect");
    exit(2);
  }
  return fd;
}

bool read_exactly(int fd, uint8_t* buf, size_t n) {
  size_t off = 0;
  while (off < n) {
    ssize_t got = read(fd, buf + off, n - off);
    if (got <= 0) return false;
    off += static_cast<size_t>(got);
  }
  return true;
}

// One client: send frames, read echoes; half the clients hang up abruptly
// partway through to exercise close_conn racing the echo sends.
void client_thread(int port, int id) {
  int fd = connect_to(port);
  uint8_t frame[64];
  int to_send = kFramesPerClient;
  int abrupt_at = (id % 2 == 0) ? kFramesPerClient / 2 : -1;
  int echoes = 0;
  for (int i = 0; i < to_send; ++i) {
    uint32_t len = 16 + static_cast<uint32_t>((id * 7 + i) % 32);
    uint32_t be = htonl(len);
    memcpy(frame, &be, 4);
    for (uint32_t b = 0; b < len; ++b) frame[4 + b] = static_cast<uint8_t>(i);
    if (write(fd, frame, 4 + len) != static_cast<ssize_t>(4 + len)) break;
    if (i == abrupt_at) {
      g_echoes_received.fetch_add(echoes);
      close(fd);  // poller echoes race this close
      return;
    }
    // read one echo frame (echoes lag sends; tolerate EOF after shutdown)
    uint8_t hdr[4];
    if (!read_exactly(fd, hdr, 4)) break;
    uint32_t elen;
    memcpy(&elen, hdr, 4);
    elen = ntohl(elen);
    std::vector<uint8_t> body(elen);
    if (!read_exactly(fd, body.data(), elen)) break;
    ++echoes;
  }
  g_echoes_received.fetch_add(echoes);
  close(fd);
}

// Pollers drain events concurrently and echo every frame back.
void poller_thread(int64_t handle) {
  std::vector<uint8_t> buf(1 << 16);
  while (!g_stop.load()) {
    int64_t conn_id = 0, len = 0;
    int ev = rapid_io_server_poll(handle, &conn_id, buf.data(),
                                  static_cast<int64_t>(buf.size()), &len, 50);
    if (ev == -1) return;
    if (ev == 1) {
      g_frames_seen.fetch_add(1);
      rapid_io_server_send(handle, conn_id, buf.data(), len);  // may race close
    }
  }
}

}  // namespace

int main() {
  int64_t handle = rapid_io_server_create("127.0.0.1", 0);
  if (handle < 0) {
    fprintf(stderr, "server create failed: %lld\n",
            static_cast<long long>(handle));
    return 2;
  }
  int port = rapid_io_server_port(handle);

  std::vector<std::thread> pollers;
  for (int i = 0; i < kPollers; ++i) pollers.emplace_back(poller_thread, handle);
  std::vector<std::thread> clients;
  for (int i = 0; i < kClients; ++i) clients.emplace_back(client_thread, port, i);
  for (auto& t : clients) t.join();

  // shutdown races the pollers' in-flight sends; they must exit via ev == -1
  rapid_io_server_shutdown(handle);
  g_stop.store(true);
  for (auto& t : pollers) t.join();

  long long seen = g_frames_seen.load();
  long long echoed = g_echoes_received.load();
  // non-abrupt clients (half) complete their full exchange lockstep, so
  // their frames and echoes are guaranteed; abrupt clients contribute a
  // partial prefix on top (observed runs: seen ~1204, echoed ~1200)
  long long non_abrupt = kClients - kClients / 2;
  long long min_expected = non_abrupt * kFramesPerClient;
  if (seen < min_expected || echoed < min_expected) {
    fprintf(stderr, "too little traffic: seen=%lld echoed=%lld\n", seen,
            echoed);
    return 1;
  }
  printf("stress ok: frames_seen=%lld echoes=%lld\n", seen, echoed);
  return 0;
}
