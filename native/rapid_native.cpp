// Native host-side control plane for rapid-tpu.
//
// The reference's runtime is JVM-native (Netty event loops, zero-allocation
// xxHash); rapid-tpu's host control plane equivalent lives here: batched
// XXH64 endpoint hashing and K-ring adjacency construction for up to 100k+
// virtual nodes, called between jitted device steps whenever the membership
// changes. Exposed as a plain C ABI for ctypes (rapid_tpu/native.py), with a
// numpy fallback when the library is not built.
//
// The XXH64 implementation follows the public xxHash specification and is
// bit-identical to rapid_tpu.hashing.xxh64 (property-tested in
// tests/test_native.py).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

namespace {

constexpr uint64_t P1 = 0x9E3779B185EBCA87ULL;
constexpr uint64_t P2 = 0xC2B2AE3D27D4EB4FULL;
constexpr uint64_t P3 = 0x165667B19E3779F9ULL;
constexpr uint64_t P4 = 0x85EBCA77C2B2AE63ULL;
constexpr uint64_t P5 = 0x27D4EB2F165667C5ULL;

inline uint64_t rotl(uint64_t x, int r) { return (x << r) | (x >> (64 - r)); }

inline uint64_t round_(uint64_t acc, uint64_t lane) {
  return rotl(acc + lane * P2, 31) * P1;
}

inline uint64_t merge_round(uint64_t acc, uint64_t val) {
  return (acc ^ round_(0, val)) * P1 + P4;
}

inline uint64_t read64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);  // little-endian hosts only (x86/ARM)
  return v;
}

inline uint64_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

uint64_t xxh64(const uint8_t* data, size_t n, uint64_t seed) {
  const uint8_t* p = data;
  const uint8_t* end = data + n;
  uint64_t acc;
  if (n >= 32) {
    uint64_t v1 = seed + P1 + P2;
    uint64_t v2 = seed + P2;
    uint64_t v3 = seed;
    uint64_t v4 = seed - P1;
    const uint8_t* limit = end - 32;
    do {
      v1 = round_(v1, read64(p));
      v2 = round_(v2, read64(p + 8));
      v3 = round_(v3, read64(p + 16));
      v4 = round_(v4, read64(p + 24));
      p += 32;
    } while (p <= limit);
    acc = rotl(v1, 1) + rotl(v2, 7) + rotl(v3, 12) + rotl(v4, 18);
    acc = merge_round(acc, v1);
    acc = merge_round(acc, v2);
    acc = merge_round(acc, v3);
    acc = merge_round(acc, v4);
  } else {
    acc = seed + P5;
  }
  acc += static_cast<uint64_t>(n);
  while (p + 8 <= end) {
    acc = rotl(acc ^ round_(0, read64(p)), 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    acc = rotl(acc ^ (read32(p) * P1), 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    acc = rotl(acc ^ (*p * P5), 11) * P1;
    ++p;
  }
  acc ^= acc >> 33;
  acc *= P2;
  acc ^= acc >> 29;
  acc *= P3;
  acc ^= acc >> 32;
  return acc;
}

}  // namespace

extern "C" {

// Hash N byte rows (zero-padded to max_len; true lengths given) with `seed`.
void rapid_xxh64_batch(const uint8_t* data, int64_t n_rows, int64_t max_len,
                       const int64_t* lengths, uint64_t seed, uint64_t* out) {
  for (int64_t i = 0; i < n_rows; ++i) {
    out[i] = xxh64(data + i * max_len, static_cast<size_t>(lengths[i]), seed);
  }
}

// Endpoint ring keys for one seed: xx(hostname)*31 + xx(4 LE port bytes)
// (Utils.AddressComparator.computeHash, Utils.java:227-230).
void rapid_endpoint_hash_batch(const uint8_t* hostnames, int64_t n_rows,
                               int64_t max_len, const int64_t* lengths,
                               const int64_t* ports, uint64_t seed,
                               uint64_t* out) {
  for (int64_t i = 0; i < n_rows; ++i) {
    uint64_t host_h =
        xxh64(hostnames + i * max_len, static_cast<size_t>(lengths[i]), seed);
    uint32_t port = static_cast<uint32_t>(ports[i]);
    uint8_t port_bytes[4];
    std::memcpy(port_bytes, &port, 4);
    out[i] = host_h * 31 + xxh64(port_bytes, 4, seed);
  }
}

// All K ring hashes at once: out[k * n_rows + i].
void rapid_ring_hashes(const uint8_t* hostnames, int64_t n_rows,
                       int64_t max_len, const int64_t* lengths,
                       const int64_t* ports, int64_t k, uint64_t* out) {
  for (int64_t ring = 0; ring < k; ++ring) {
    rapid_endpoint_hash_batch(hostnames, n_rows, max_len, lengths, ports,
                              static_cast<uint64_t>(ring), out + ring * n_rows);
  }
}

// Build subjects/observers adjacency over the active membership.
// ring_hashes: [K, C] (as produced by rapid_ring_hashes); active: [C] uint8;
// subjects/observers: [C, K] int32, pre-filled by the caller with self-ids.
// Ordering is by SIGNED hash (Long.compare domain, Utils.java:216-221).
void rapid_build_adjacency(const uint64_t* ring_hashes, const uint8_t* active,
                           int64_t capacity, int64_t k, int32_t* subjects,
                           int32_t* observers) {
  std::vector<int32_t> active_idx;
  active_idx.reserve(capacity);
  for (int64_t i = 0; i < capacity; ++i) {
    if (active[i]) active_idx.push_back(static_cast<int32_t>(i));
  }
  const int64_t n = static_cast<int64_t>(active_idx.size());
  if (n <= 1) return;
  std::vector<int32_t> order(active_idx);
  for (int64_t ring = 0; ring < k; ++ring) {
    const uint64_t* h = ring_hashes + ring * capacity;
    std::sort(order.begin(), order.end(), [h](int32_t a, int32_t b) {
      return static_cast<int64_t>(h[a]) < static_cast<int64_t>(h[b]);
    });
    for (int64_t t = 0; t < n; ++t) {
      int32_t node = order[t];
      subjects[node * k + ring] = order[(t - 1 + n) % n];
      observers[node * k + ring] = order[(t + 1) % n];
    }
  }
}

// Chained configuration-id fold: h=1; h = h*37 + x_i (mod 2^64).
uint64_t rapid_config_fold(const uint64_t* xs, int64_t n) {
  uint64_t h = 1;
  for (int64_t i = 0; i < n; ++i) h = h * 37 + xs[i];
  return h;
}

}  // extern "C"
