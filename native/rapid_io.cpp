// Native framed-TCP reactor: one epoll thread replaces thread-per-connection.
//
// The runtime-IO analogue of the reference's Netty event-loop group
// (SharedResources.java:48-67 lazily creates one NIO event-loop shared by
// every channel; NettyClientServer.java:65 builds both transport halves on
// it). The Python transport (rapid_tpu/messaging/tcp.py) spends one blocking
// reader thread per accepted connection; this reactor multiplexes every
// connection of a server onto a single epoll loop in native code, handing
// complete frames to Python through a poll()-style event queue.
//
// Wire format: identical to rapid_tpu.messaging.codec -- a big-endian u32
// length prefix followed by the payload (the request-no/type-tag/msgpack
// envelope is parsed in Python; the reactor only frames bytes).
//
// Contract (all functions exported with C linkage, driven via ctypes):
//   rapid_io_server_create(host, port)        -> handle >= 1, or -errno
//   rapid_io_server_port(h)                   -> bound port (after create)
//   rapid_io_server_poll(h, &conn, buf, cap, &len, timeout_ms)
//       -> 0 none, 1 frame (copied to buf; if it exceeds cap, len is set,
//          the event stays queued, nothing is copied -- retry with a bigger
//          buffer), 2 connection closed, -1 server shut down
//   rapid_io_server_send(h, conn, data, len)  -> 0 ok, -1 connection gone
//   rapid_io_server_shutdown(h)               -> idempotent; wakes pollers
//
// Threading: create/shutdown from any thread; poll from any number of
// threads (events are consumed exactly once); send from any thread and
// never blocks -- frames are serialized per connection, and bytes the
// socket won't take are queued (capped) for the reactor's EPOLLOUT flush.

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr uint64_t kMaxFrame = 64ull * 1024 * 1024;  // parity with tcp.py

struct Conn {
  int fd = -1;
  int64_t id = 0;
  std::vector<uint8_t> rbuf;
  // write side (guarded by write_mu): sends that would block are queued and
  // flushed by the reactor on EPOLLOUT, so rapid_io_server_send never stalls
  // the calling thread on a slow peer
  std::mutex write_mu;
  std::deque<std::vector<uint8_t>> wqueue;
  size_t woff = 0;      // bytes of wqueue.front() already written
  size_t wbytes = 0;    // total queued bytes (capped)
  bool want_write = false;  // EPOLLOUT currently armed
  std::atomic<bool> open{true};
};

constexpr size_t kMaxQueuedWrite = 64ull * 1024 * 1024;

// epoll_event.data.u64 tags: connection events carry the conn id (>= 1), so
// a stale event left in an epoll_wait batch after its connection was closed
// -- and whose fd number may already be reused by an accept later in the
// same batch -- resolves to a dead id and is dropped, instead of being
// misattributed to the new connection.
constexpr uint64_t kListenTag = 0;
constexpr uint64_t kWakeTag = ~0ull;

struct Event {
  int type;  // 1 = frame, 2 = closed
  int64_t conn_id;
  std::vector<uint8_t> frame;
};

struct Server {
  int listen_fd = -1;
  int epfd = -1;
  int wake_pipe[2] = {-1, -1};
  int port = 0;
  std::thread loop;
  std::atomic<bool> running{true};

  std::mutex mu;  // conns + events + cv
  std::condition_variable cv;
  std::unordered_map<int64_t, std::shared_ptr<Conn>> conns;
  std::deque<Event> events;
  int64_t next_conn_id = 1;
};

std::mutex g_mu;
std::unordered_map<int64_t, std::shared_ptr<Server>> g_servers;
int64_t g_next_handle = 1;

std::shared_ptr<Server> lookup(int64_t handle) {
  std::lock_guard<std::mutex> lk(g_mu);
  auto it = g_servers.find(handle);
  return it == g_servers.end() ? nullptr : it->second;
}

int set_nonblocking(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  if (flags < 0) return -1;
  return fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

void arm_writable(Server& srv, Conn& conn, bool on) {
  // caller holds conn.write_mu
  if (conn.want_write == on) return;
  conn.want_write = on;
  epoll_event ev{};
  ev.events = EPOLLIN | (on ? EPOLLOUT : 0u);
  ev.data.u64 = static_cast<uint64_t>(conn.id);
  epoll_ctl(srv.epfd, EPOLL_CTL_MOD, conn.fd, &ev);
}

// Write as much of the queue as the socket accepts; returns false when the
// connection errored and must be torn down. Caller holds conn.write_mu.
bool flush_wqueue(Server& srv, Conn& conn) {
  while (!conn.wqueue.empty()) {
    auto& front = conn.wqueue.front();
    ssize_t sent = send(conn.fd, front.data() + conn.woff,
                        front.size() - conn.woff, MSG_NOSIGNAL);
    if (sent > 0) {
      conn.woff += static_cast<size_t>(sent);
      conn.wbytes -= static_cast<size_t>(sent);
      if (conn.woff == front.size()) {
        conn.wqueue.pop_front();
        conn.woff = 0;
      }
    } else if (sent < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
      arm_writable(srv, conn, true);
      return true;
    } else if (sent < 0 && errno == EINTR) {
      continue;
    } else {
      return false;
    }
  }
  arm_writable(srv, conn, false);
  return true;
}

void enqueue_event(Server& srv, Event ev) {
  {
    std::lock_guard<std::mutex> lk(srv.mu);
    srv.events.push_back(std::move(ev));
  }
  srv.cv.notify_one();
}

// Split rbuf into complete frames; returns false on a protocol violation
// (oversized frame) -- the connection is killed like tcp.py's ValueError.
bool drain_frames(Server& srv, Conn& conn) {
  size_t off = 0;
  while (conn.rbuf.size() - off >= 4) {
    uint32_t be;
    memcpy(&be, conn.rbuf.data() + off, 4);
    uint64_t need = ntohl(be);
    if (need > kMaxFrame) return false;
    if (conn.rbuf.size() - off - 4 < need) break;
    Event ev;
    ev.type = 1;
    ev.conn_id = conn.id;
    ev.frame.assign(conn.rbuf.begin() + off + 4,
                    conn.rbuf.begin() + off + 4 + need);
    enqueue_event(srv, std::move(ev));
    off += 4 + need;
  }
  if (off > 0) conn.rbuf.erase(conn.rbuf.begin(), conn.rbuf.begin() + off);
  return true;
}

void close_conn(Server& srv, int64_t conn_id) {
  std::shared_ptr<Conn> conn;
  {
    std::lock_guard<std::mutex> lk(srv.mu);
    auto it = srv.conns.find(conn_id);
    if (it == srv.conns.end()) return;  // already closed (e.g. stale event)
    conn = it->second;
    srv.conns.erase(it);
  }
  conn->open.store(false);
  // FIN before taking write_mu, then close under it: concurrent senders
  // fail fast on the shut-down socket and can never write into a reused
  // fd number
  shutdown(conn->fd, SHUT_RDWR);
  std::lock_guard<std::mutex> wl(conn->write_mu);
  epoll_ctl(srv.epfd, EPOLL_CTL_DEL, conn->fd, nullptr);
  close(conn->fd);
  Event ev;
  ev.type = 2;
  ev.conn_id = conn->id;
  enqueue_event(srv, std::move(ev));
}

void reactor_loop(std::shared_ptr<Server> srv) {
  epoll_event evs[64];
  std::vector<uint8_t> chunk(256 * 1024);
  while (srv->running.load()) {
    int n = epoll_wait(srv->epfd, evs, 64, 500);
    if (n < 0) {
      if (errno == EINTR) continue;
      break;
    }
    for (int i = 0; i < n && srv->running.load(); ++i) {
      uint64_t tag = evs[i].data.u64;
      if (tag == kWakeTag) {
        uint8_t b;
        while (read(srv->wake_pipe[0], &b, 1) > 0) {
        }
        continue;
      }
      if (tag == kListenTag) {
        for (;;) {
          int cfd = accept(srv->listen_fd, nullptr, nullptr);
          if (cfd < 0) break;
          if (set_nonblocking(cfd) < 0) {
            close(cfd);
            continue;
          }
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          auto conn = std::make_shared<Conn>();
          conn->fd = cfd;
          {
            std::lock_guard<std::mutex> lk(srv->mu);
            conn->id = srv->next_conn_id++;
            srv->conns[conn->id] = conn;
          }
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.u64 = static_cast<uint64_t>(conn->id);
          if (epoll_ctl(srv->epfd, EPOLL_CTL_ADD, cfd, &ev) < 0) {
            close_conn(*srv, conn->id);
          }
        }
        continue;
      }
      // connection readable (or errored); a dead id means the connection was
      // closed earlier in this batch -- drop the stale event (its fd number
      // may already belong to a newly accepted connection)
      std::shared_ptr<Conn> conn;
      {
        std::lock_guard<std::mutex> lk(srv->mu);
        auto it = srv->conns.find(static_cast<int64_t>(tag));
        if (it != srv->conns.end()) conn = it->second;
      }
      if (!conn) continue;
      int fd = conn->fd;
      bool dead = (evs[i].events & (EPOLLHUP | EPOLLERR)) != 0;
      if (!dead && (evs[i].events & EPOLLOUT)) {
        std::lock_guard<std::mutex> wl(conn->write_mu);
        if (!flush_wqueue(*srv, *conn)) dead = true;
      }
      if (!(evs[i].events & EPOLLIN) && !dead) continue;
      while (!dead) {
        ssize_t got = read(fd, chunk.data(), chunk.size());
        if (got > 0) {
          conn->rbuf.insert(conn->rbuf.end(), chunk.data(),
                            chunk.data() + got);
          if (!drain_frames(*srv, *conn)) dead = true;
          if (static_cast<size_t>(got) < chunk.size()) break;
        } else if (got == 0) {
          dead = true;  // peer sent FIN
        } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
          break;
        } else if (errno == EINTR) {
          continue;
        } else {
          dead = true;
        }
      }
      if (dead) close_conn(*srv, conn->id);
    }
  }
}

}  // namespace

extern "C" {

int64_t rapid_io_server_create(const char* host, int port) {
  auto srv = std::make_shared<Server>();
  srv->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (srv->listen_fd < 0) return -errno;
  int one = 1;
  setsockopt(srv->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    close(srv->listen_fd);
    return -EINVAL;
  }
  if (bind(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
          0 ||
      listen(srv->listen_fd, 128) < 0 || set_nonblocking(srv->listen_fd) < 0) {
    int err = errno;
    close(srv->listen_fd);
    return -err;
  }
  socklen_t alen = sizeof(addr);
  getsockname(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  srv->port = ntohs(addr.sin_port);

  if (pipe(srv->wake_pipe) < 0 ||
      set_nonblocking(srv->wake_pipe[0]) < 0 ||
      (srv->epfd = epoll_create1(0)) < 0) {
    int err = errno;
    close(srv->listen_fd);
    if (srv->wake_pipe[0] >= 0) close(srv->wake_pipe[0]);
    if (srv->wake_pipe[1] >= 0) close(srv->wake_pipe[1]);
    return -err;
  }
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.u64 = kListenTag;
  epoll_ctl(srv->epfd, EPOLL_CTL_ADD, srv->listen_fd, &ev);
  ev.data.u64 = kWakeTag;
  epoll_ctl(srv->epfd, EPOLL_CTL_ADD, srv->wake_pipe[0], &ev);

  srv->loop = std::thread(reactor_loop, srv);

  std::lock_guard<std::mutex> lk(g_mu);
  int64_t handle = g_next_handle++;
  g_servers[handle] = srv;
  return handle;
}

int rapid_io_server_port(int64_t handle) {
  auto srv = lookup(handle);
  return srv ? srv->port : -1;
}

int rapid_io_server_poll(int64_t handle, int64_t* conn_id, uint8_t* buf,
                         int64_t buf_cap, int64_t* len, int timeout_ms) {
  auto srv = lookup(handle);
  if (!srv) return -1;
  std::unique_lock<std::mutex> lk(srv->mu);
  if (!srv->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), [&] {
        return !srv->events.empty() || !srv->running.load();
      })) {
    return 0;  // timeout
  }
  if (srv->events.empty()) return srv->running.load() ? 0 : -1;
  Event& ev = srv->events.front();
  *conn_id = ev.conn_id;
  if (ev.type == 1) {
    *len = static_cast<int64_t>(ev.frame.size());
    if (*len > buf_cap) return 1;  // stays queued; caller grows the buffer
    memcpy(buf, ev.frame.data(), ev.frame.size());
  } else {
    *len = 0;
  }
  int type = ev.type;
  srv->events.pop_front();
  return type;
}

int rapid_io_server_send(int64_t handle, int64_t conn_id, const uint8_t* data,
                         int64_t len) {
  auto srv = lookup(handle);
  if (!srv || len < 0 || static_cast<uint64_t>(len) > kMaxFrame) return -1;
  std::shared_ptr<Conn> conn;
  {
    std::lock_guard<std::mutex> lk(srv->mu);
    auto it = srv->conns.find(conn_id);
    if (it == srv->conns.end()) return -1;
    conn = it->second;
  }
  uint32_t be = htonl(static_cast<uint32_t>(len));
  std::vector<uint8_t> out(4 + len);
  memcpy(out.data(), &be, 4);
  if (len > 0) memcpy(out.data() + 4, data, len);

  // Never blocks: bytes the socket won't take are queued for the reactor's
  // EPOLLOUT flush, so one stalled peer cannot head-of-line-block the
  // caller (the reply path runs on the dispatcher thread).
  std::lock_guard<std::mutex> wl(conn->write_mu);
  if (!conn->open.load()) return -1;
  if (conn->wbytes + out.size() > kMaxQueuedWrite) return -1;  // peer stalled
  if (conn->wqueue.empty()) {
    size_t off = 0;
    while (off < out.size()) {
      ssize_t sent =
          send(conn->fd, out.data() + off, out.size() - off, MSG_NOSIGNAL);
      if (sent > 0) {
        off += static_cast<size_t>(sent);
      } else if (sent < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        break;
      } else if (sent < 0 && errno == EINTR) {
        continue;
      } else {
        return -1;
      }
    }
    if (off == out.size()) return 0;
    out.erase(out.begin(), out.begin() + off);
  }
  conn->wbytes += out.size();
  conn->wqueue.push_back(std::move(out));
  arm_writable(*srv, *conn, true);
  return 0;
}

void rapid_io_server_shutdown(int64_t handle) {
  std::shared_ptr<Server> srv;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_servers.find(handle);
    if (it == g_servers.end()) return;
    srv = it->second;
    g_servers.erase(it);
  }
  srv->running.store(false);
  uint8_t b = 1;
  ssize_t ignored = write(srv->wake_pipe[1], &b, 1);
  (void)ignored;
  srv->cv.notify_all();
  if (srv->loop.joinable()) srv->loop.join();

  std::vector<std::shared_ptr<Conn>> conns;
  {
    std::lock_guard<std::mutex> lk(srv->mu);
    for (auto& kv : srv->conns) conns.push_back(kv.second);
    srv->conns.clear();
  }
  for (auto& conn : conns) {
    // same exclusion dance as close_conn: flip open and FIN first (peers
    // blocked in recv() sense liveness by EOF), then close under write_mu
    // so no in-flight send() can write into a reused fd number
    conn->open.store(false);
    shutdown(conn->fd, SHUT_RDWR);
    std::lock_guard<std::mutex> wl(conn->write_mu);
    close(conn->fd);
  }
  shutdown(srv->listen_fd, SHUT_RDWR);
  close(srv->listen_fd);
  close(srv->epfd);
  close(srv->wake_pipe[0]);
  close(srv->wake_pipe[1]);
  srv->cv.notify_all();
}

}  // extern "C"
