"""Headline benchmark: time-to-stable-view for a 100k-node membership
simulation with a 1% correlated crash burst, on real TPU hardware.

BASELINE.json north star: "simulate a 100k-node cluster converging on a 1%
correlated-failure cut in <5s ... with cut-set identical to the JVM
reference". value = wall ms from fault injection to the decided view (jit
warmed); vs_baseline = value / 5000ms (fraction of the north-star budget;
< 1.0 means the target is beaten). Cut-set parity is asserted before
reporting: the decided cut must be exactly the crashed set, and the resulting
configuration ID is computed with the bit-exact JVM hash chain.

Prints exactly one JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import threading
import time

import numpy as np

N_NODES = 100_000
FAIL_FRACTION = 0.01
BASELINE_MS = 5000.0  # north-star budget (BASELINE.json)

# Fail fast instead of hanging forever when the accelerator is unreachable
# (the remote-TPU tunnel blocks indefinitely inside device init when its
# upstream is down): a warmed 100k run takes ~1 min end to end, so if the
# watchdog fires something is broken, and a loud error beats a silent hang.
WATCHDOG_S = 15 * 60


def _arm_watchdog() -> None:
    def fire() -> None:
        print(
            f"bench.py watchdog: no result after {WATCHDOG_S}s -- the "
            "accelerator is likely unreachable (device init hangs when the "
            "TPU tunnel's upstream is down). No measurement was produced.",
            file=sys.stderr,
            flush=True,
        )
        os._exit(17)

    timer = threading.Timer(WATCHDOG_S, fire)
    timer.daemon = True
    timer.start()


def warmed_run(n_nodes: int, seed: int, fail_fraction: float = FAIL_FRACTION):
    """The single definition of the warmed measurement (shared with
    experiments/scaling_sweep.py so the published sweep can never drift from
    the headline): compile on an identical-shape run, then time a fresh
    simulator from fault injection to the decided view, asserting cut-set
    parity. Returns (wall_ms, record, build_s, warmup_wall_s)."""
    from rapid_tpu.sim.driver import Simulator

    rng = np.random.default_rng(seed)
    n_fail = max(1, int(n_nodes * fail_fraction))

    t_build0 = time.perf_counter()
    sim = Simulator(n_nodes, seed=seed)
    build_s = time.perf_counter() - t_build0

    victims = rng.choice(n_nodes, size=n_fail, replace=False)
    sim.crash(victims)
    warm = sim.run_until_decision(max_rounds=16, batch=16)
    assert warm is not None and set(warm.cut) == set(victims), "warmup parity failed"
    warm_wall = warm.wall_time_s

    sim2 = Simulator(n_nodes, seed=seed + 4444)
    sim2.ready()  # drain construction from the device queue
    victims2 = rng.choice(n_nodes, size=n_fail, replace=False)
    sim2.crash(victims2)
    t0 = time.perf_counter()
    record = sim2.run_until_decision(max_rounds=16, batch=16)
    wall_ms = (time.perf_counter() - t0) * 1000.0

    assert record is not None, "no decision reached"
    assert set(record.cut) == set(victims2), "cut-set parity violated"
    assert record.membership_size == n_nodes - len(victims2)
    return wall_ms, record, build_s, warm_wall


def main() -> None:
    _arm_watchdog()
    wall_ms, record, build_s, warm_wall = warmed_run(N_NODES, seed=1234)

    print(
        json.dumps(
            {
                "metric": "time_to_stable_view_100k_nodes_1pct_crash_sim",
                "value": round(wall_ms, 1),
                "unit": "ms",
                "vs_baseline": round(wall_ms / BASELINE_MS, 4),
            }
        )
    )
    print(
        f"# membership={N_NODES}->{record.membership_size} cut={len(record.cut)} nodes "
        f"virtual_time={record.virtual_time_ms}ms config_id={record.configuration_id} "
        f"build={build_s:.1f}s warmup_wall={warm_wall:.1f}s",
        file=sys.stderr,
    )


if __name__ == "__main__":
    main()
