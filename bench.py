"""Headline benchmark: time-to-stable-view for a 100k-node membership
simulation with a 1% correlated crash burst, on real TPU hardware.

BASELINE.json north star: "simulate a 100k-node cluster converging on a 1%
correlated-failure cut in <5s ... with cut-set identical to the JVM
reference". value = wall ms from fault injection to the decided view (jit
warmed); vs_baseline = value / 5000ms (fraction of the north-star budget;
< 1.0 means the target is beaten). Cut-set parity is asserted before
reporting: the decided cut must be exactly the crashed set, and the resulting
configuration ID is computed with the bit-exact JVM hash chain.

Prints exactly one JSON line:
  {"metric", "value", "unit", "vs_baseline", "backend", "sweep",
   "wan_stable_view"}
where "sweep" is the warmed scaling curve (1k/10k/100k/1M on TPU; the 1M
point is skipped on CPU), each entry measured by the same warmed_run as the
headline so the curve can never drift from it, and "wan_stable_view" is the
WAN dimension: stable-view latency vs inter-region RTT (WAN_RTTS_MS), the
topology compiled onto the device plane's delivery groups.

Exit-code contract (the driver records rc alongside the JSON):
  0   measurement produced; TPU wall within the regression budget
  17  accelerator unreachable -- the remote-TPU tunnel's upstream is down
      (device init hangs forever in that state, so availability is probed
      in killable subprocesses with bounded retries before any jax import
      in this process). Infrastructure outage, NOT a code regression.
  18  measurement produced (JSON printed) but the warmed 100k wall on a
      real TPU exceeded TPU_BUDGET_MS -- a perf regression the driver's
      artifact catches even though the plain CPU test battery cannot
      (tests/test_bench_regression.py guards CPU wall + exact protocol
      time; this is the TPU-side structural guard).
  other nonzero: crash / parity-assertion failure -- a correctness bug.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

# The bench always runs with jitwatch armed (rapid_tpu/runtime/jitwatch.py):
# every sweep point reports its compile count + compile wall-time split into
# warmup vs steady state, and the plain (non-placement) timed runs execute
# inside a jitwatch timed window -- a steady-state recompile or an implicit
# host transfer fails the bench instead of silently inflating the number.
# Must be set before anything imports rapid_tpu (the seam samples it at
# module import). Override with RAPID_JITWATCH=0 for A/B overhead runs.
os.environ.setdefault("RAPID_JITWATCH", "1")

N_NODES = 100_000
FAIL_FRACTION = 0.01
BASELINE_MS = 5000.0  # north-star budget (BASELINE.json)

# TPU-side wall budget for the warmed 100k decision (rc 18 above it).
# Last driver-verified record: BENCH_r02.json = 122.8 ms; round-3 builder
# measurements ranged ~115-150 ms against a noisy tunnel. 250 ms flags a
# structural regression (lost early-exit, an extra fetched buffer ~= +100 ms)
# without tripping on ordinary day-to-day tunnel latency variance.
TPU_BUDGET_MS = 250.0

# Device-availability probe: attempt timeouts + pauses, all in subprocesses
# (a hung device init cannot be interrupted in-process; the wedged client
# would also hold the single-client tunnel). Total worst case ~8.5 min.
PROBE_TIMEOUTS_S = (90, 150, 240)
PROBE_PAUSE_S = 15

# Backstop for anything unexpectedly hanging AFTER the probe succeeded
# (e.g. the tunnel dying mid-measurement). Probe (~8.5 min) + warmed
# headline + sweep (~5 min) fit comfortably.
WATCHDOG_S = 20 * 60


# Progress shared with the watchdog: once the headline measurement exists it
# is the round's artifact, and a later hang (e.g. the 1M sweep point jitting
# against a dying tunnel) must emit it rather than destroy it.
_PROGRESS: dict = {
    "headline": None, "backend": None, "sweep": [], "wan": None,
    "serving": None, "messaging": None, "gray_detection": None,
    "recovery": None, "hierarchy": None,
}

# jitwatch compile accounting of the most recent warmed_run (warmup vs
# steady split); run_sweep copies it into each sweep entry and main() into
# the headline, so every JSON data point carries its own compile story.
_LAST_JIT_STATS: dict = {}

# Serving dimension: open-loop Get/Put load against the serving-plane
# mirror (replicated KV over placement + handoff), measured through a view
# change. Arrivals are scheduled by rate (slo/sli.py OpenLoopGenerator:
# seeded expovariate inter-arrivals, zipfian keys, a simulated client
# population) independently of completions, so measured latency includes
# queueing delay -- the coordinated-omission fix over the old closed-loop
# driver. Three windows -- steady state, the churn window between the crash
# and the decided view (dead leaders cost redirect hops + quorum reads),
# and post-view -- each reporting throughput + p50/p99 + the full latency
# histogram on virtual time, so the numbers are deterministic per seed.
SERVING_N_NODES = 64
SERVING_PARTITIONS = 256
SERVING_KEYS = 64
SERVING_OPS = {"steady": 300, "view_change_window": 150, "post_view": 150}
SERVING_PUT_FRACTION = 0.2
SERVING_RATE_PER_S = 600.0     # ~0.6x capacity steady, >1x during redirects
SERVING_ZIPF_S = 1.1
SERVING_CLIENTS = 1_000_000
# burn windows compressed onto bench-scale virtual time: fast pair
# 5m/1h -> 300ms/3.6s, so a churn window of queueing shows up in-run
SERVING_SLO_WINDOW_SCALE = 0.001

# WAN dimension: stable-view latency vs inter-region round-trip time. Two
# regions, 2k nodes, a 1% crash in the mix; the topology compiles to
# delivery groups + broadcast-delay rounds on the device plane (see
# rapid_tpu/faults.py:apply_topology). 0 = the flat-fabric control point.
WAN_N_NODES = 2_000
WAN_RTTS_MS = (0, 500, 1000)

# Hierarchy dimension: flat vs hierarchical A/B on the same seed. The flat
# anchor is sized at the scale Rapid's published evaluation stops (2k
# members in one flat configuration); the hierarchical leg seats 10x that
# across HIER_CELLS cells and must still converge the same 1% crash with
# cut parity, a composed global view matching a from-scratch recompute,
# and composition work billed per touched cell (O(cells), not O(members)).
HIER_FLAT_N = 2_000
HIER_SCALE_FACTOR = 10
HIER_CELLS = 8
HIER_PARENT_ROUND_MS = 4

# Messaging dimension: real-socket transport throughput on loopback. Two
# workloads -- a pipelined request/response pair (RPC round-trip rate) and a
# 16-node broadcast storm (every node broadcasts BURST votes per round to
# every peer through the flush-window batching broadcaster) -- plus an
# in-bench thread-per-message baseline reproducing the pre-event-loop
# transport shape (blocking sendall per message: one write syscall per
# message by construction) for the A/B speedup and syscall-reduction
# numbers in the JSON line.
# Gray-detection dimension: detection->decision latency of the simulator's
# gray-aware FD mirror (SimConfig.fd_gray_confirm) vs the static cumulative
# counter, A/B on an identical WAN-shaped cluster replaying the same
# slow-node plan. Two fault shapes: a node that turns gray and stays gray
# (gray_slow_node) and one oscillating slow/healthy (gray_flapping), whose
# healthy gaps reset the adaptive miss streak but never the static counter.
GRAY_N_NODES = 64
GRAY_DELAY_MS = 5_000
GRAY_CONFIRM = 3          # adaptive: sustained-miss streak that fires
GRAY_WARMUP = 3           # successful probes before gray scoring engages
GRAY_WINDOWS = {
    # fault opens after 3 healthy probe intervals (>= GRAY_WARMUP)
    "gray_slow_node": ((3_000, None),),
    # three 6 s slow windows with 6 s healthy gaps: 6 misses per window,
    # under the static threshold of 10, so the static counter must straddle
    # two windows while the adaptive streak concludes inside the first
    "gray_flapping": ((3_000, 9_000), (15_000, 21_000), (27_000, 33_000)),
}

# Recovery dimension: cold-start replay wall time of the durability plane's
# log-over-snapshot recovery (rapid_tpu/durability), on a grid of log length
# x snapshot recency. The replayed-record count at each point is exact and
# deterministic per seed (records % snapshot_every, or the full log when
# snapshots are off) and asserted, as is byte-identical recovered content;
# the wall number rides the JSON line as recovery_time_ms.
RECOVERY_LOG_RECORDS = (256, 1024)
RECOVERY_SNAPSHOT_EVERY = (0, 256)   # 0 = never snapshot: full-log replay
RECOVERY_PARTITIONS = 32
RECOVERY_VALUE_BYTES = 512

MESSAGING_PAIR_MSGS = 2_000
MESSAGING_STORM_NODES = 16
MESSAGING_STORM_ROUNDS = 40
MESSAGING_STORM_BURST = 8
MESSAGING_FLUSH_WINDOW_MS = 5
MESSAGING_DEADLINE_S = 120.0


def _stable_view_hist() -> "dict | None":
    """Virtual-time time_to_stable_view_ms histogram accumulated across every
    simulator this process ran (headline + sweep), pulled off the global
    registry. None when nothing was recorded (e.g. the device layer stubbed
    out in the contract tests)."""
    try:
        from rapid_tpu.observability import global_metrics

        snap = global_metrics().histogram("time_to_stable_view_ms", plane="sim")
        return snap if snap["count"] else None
    except Exception:  # noqa: BLE001 -- telemetry must never sink the artifact
        return None


def _placement_hist() -> "dict | None":
    """Partitions-moved-per-rebalance histogram from the sim plane's
    placement updates (populated by the sweep sizes that enable placement).
    None when placement never ran."""
    try:
        from rapid_tpu.observability import global_metrics

        snap = global_metrics().histogram(
            "placement.partitions_moved", plane="sim"
        )
        return snap if snap["count"] else None
    except Exception:  # noqa: BLE001 -- telemetry must never sink the artifact
        return None


def _handoff_hist() -> "dict | None":
    """Bytes-moved-per-completed-session histogram from the sim plane's
    handoff transfers (populated by the sweep sizes that enable handoff).
    None when handoff never ran."""
    try:
        from rapid_tpu.observability import global_metrics

        snap = global_metrics().histogram(
            "handoff.session_bytes", plane="sim"
        )
        return snap if snap["count"] else None
    except Exception:  # noqa: BLE001 -- telemetry must never sink the artifact
        return None


def _handoff_completed() -> int:
    """Completed handoff session count summed over the global registry tree
    (``get`` only reads one registry's own counters; live children -- each
    Simulator's plane=sim registry -- are reachable through ``collect``).
    0 when handoff never ran or telemetry is unavailable."""
    try:
        from rapid_tpu.observability import global_metrics

        return sum(
            value
            for kind, name, labels, value in global_metrics().collect()
            if kind == "counter" and name == "handoff.sessions_completed"
            and labels.get("plane") == "sim"
        )
    except Exception:  # noqa: BLE001 -- telemetry must never sink the artifact
        return 0


def _flag_value(flag: str) -> "str | None":
    """Tolerant --flag VALUE / --flag=VALUE scan. argparse would choke on
    pytest's argv when the contract tests call main() in-process."""
    argv = sys.argv[1:]
    for i, arg in enumerate(argv):
        if arg == flag and i + 1 < len(argv):
            return argv[i + 1]
        if arg.startswith(flag + "="):
            return arg.split("=", 1)[1]
    return None


def _write_telemetry() -> None:
    """Optional --trace-out / --metrics-out exports of the run's telemetry."""
    trace_out, metrics_out = _flag_value("--trace-out"), _flag_value("--metrics-out")
    if trace_out is None and metrics_out is None:
        return
    from rapid_tpu.observability import write_chrome_trace, write_prometheus

    if trace_out is not None:
        write_chrome_trace(trace_out)
        print(f"bench.py: wrote Chrome trace to {trace_out}", file=sys.stderr, flush=True)
    if metrics_out is not None:
        write_prometheus(metrics_out)
        print(f"bench.py: wrote Prometheus text to {metrics_out}", file=sys.stderr, flush=True)


def _device_info() -> dict:
    """Device identity + topology stamped on every emitted JSON line, so a
    curve point is attributable to the hardware that produced it (a v5e-8
    number and a CPU number must never be comparable by accident). Never
    raises: on the rc-17 outage path jax may be unimportable or deviceless,
    and the artifact still has to go out."""
    try:
        import jax

        devices = jax.devices()
        kinds = sorted({d.device_kind for d in devices})
        return {
            "device_kind": ",".join(kinds),
            "device_count": len(devices),
            "process_count": jax.process_count(),
            # the sim plane shards over a 1-D mesh of every device
            # (shard.engine.make_mesh); report that shape as the topology
            "mesh_shape": {"nodes": len(devices)},
        }
    except Exception:  # noqa: BLE001 -- telemetry must never sink the artifact
        return {
            "device_kind": None, "device_count": 0,
            "process_count": 0, "mesh_shape": None,
        }


def _emit_json(headline: dict, backend: str, sweep: list) -> None:
    merged = list(sweep) + [
        {
            "n": N_NODES,
            "warmed_wall_ms": headline["value"],
            "virtual_ms": headline["virtual_ms"],
            "cut_ok": True,
            **{k: v for k, v in headline.items() if k.startswith("jit_")},
        }
    ]
    merged.sort(key=lambda e: e.get("n", 1 << 62))
    print(
        json.dumps(
            {
                "metric": "time_to_stable_view_100k_nodes_1pct_crash_sim",
                "value": headline["value"],
                "unit": "ms",
                "vs_baseline": round(headline["value"] / BASELINE_MS, 4),
                "backend": backend,
                **_device_info(),
                "sweep": merged,
                "wan_stable_view": _PROGRESS["wan"],
                "serving_qps": _PROGRESS["serving"],
                "messaging_throughput": _PROGRESS["messaging"],
                "gray_detection_ms": _PROGRESS["gray_detection"],
                "recovery_time_ms": _PROGRESS["recovery"],
                "hierarchy_scale": _PROGRESS["hierarchy"],
                "time_to_stable_view_ms": _stable_view_hist(),
                "placement_partitions_moved": _placement_hist(),
                "handoff_session_bytes": _handoff_hist(),
            }
        ),
        flush=True,
    )


def _emit_outage_json(reason: str) -> None:
    """rc-17 paths still owe the harness one well-formed JSON line: no
    measurement happened, but ``"outage": true`` plus whatever CPU/sim-plane
    telemetry accumulated before the tunnel died (histograms in particular)
    lets the curve distinguish 'infrastructure down' from 'emitted nothing'
    without parsing stderr."""
    histograms = None
    try:
        from rapid_tpu.observability import json_snapshot

        histograms = json_snapshot()["histograms"] or None
    except Exception:  # noqa: BLE001 -- telemetry must never sink the artifact
        histograms = None
    print(
        json.dumps(
            {
                "metric": "time_to_stable_view_100k_nodes_1pct_crash_sim",
                "value": None,
                "unit": "ms",
                "outage": True,
                "reason": reason,
                "backend": _PROGRESS["backend"],
                **_device_info(),
                "time_to_stable_view_ms": _stable_view_hist(),
                "histograms": histograms,
            }
        ),
        flush=True,
    )


def _on_watchdog() -> int:
    """The watchdog's decision, separated from os._exit for testability:
    with the headline already measured, the hang is in the sweep tail --
    emit the partial artifact and apply the normal rc contract; with no
    headline, nothing was measured (rc 17)."""
    headline = _PROGRESS["headline"]
    if headline is not None:
        sweep = list(_PROGRESS["sweep"])
        sweep.append({"error": f"watchdog after {WATCHDOG_S}s mid-sweep"})
        _emit_json(headline, _PROGRESS["backend"] or "unknown", sweep)
        print(
            f"bench.py watchdog: hang after {WATCHDOG_S}s with the "
            "headline already measured; emitted the partial artifact.",
            file=sys.stderr,
            flush=True,
        )
        if _PROGRESS["backend"] == "tpu" and headline["value"] > TPU_BUDGET_MS:
            return 18
        return 0
    _emit_outage_json(f"watchdog after {WATCHDOG_S}s with no headline")
    print(
        f"bench.py watchdog: no result after {WATCHDOG_S}s -- the "
        "accelerator likely became unreachable mid-run (the TPU tunnel "
        "hangs rather than erroring when its upstream drops). No "
        "measurement was produced.",
        file=sys.stderr,
        flush=True,
    )
    return 17


def _arm_watchdog() -> None:
    timer = threading.Timer(WATCHDOG_S, lambda: os._exit(_on_watchdog()))
    timer.daemon = True
    timer.start()


def _probe_backend_once(timeout_s: float) -> "str | None":
    """Device init in a killable subprocess: returns the default backend
    platform name if it completes within timeout_s, else None."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.default_backend())"],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0:
        return None
    lines = out.stdout.strip().splitlines()
    return lines[-1] if lines else None


def probe_backend() -> "str | None":
    """Bounded-retry availability probe (the tunnel outage seen in rounds
    3-4 lasted hours, but brief relay blips recover in seconds -- retrying
    across a few minutes distinguishes the two without burning the round)."""
    for i, t in enumerate(PROBE_TIMEOUTS_S):
        backend = _probe_backend_once(t)
        if backend is not None:
            return backend
        print(
            f"bench.py: device probe {i + 1}/{len(PROBE_TIMEOUTS_S)} timed "
            f"out after {t}s",
            file=sys.stderr,
            flush=True,
        )
        if i + 1 < len(PROBE_TIMEOUTS_S):
            time.sleep(PROBE_PAUSE_S)
    return None


def warmed_run(n_nodes: int, seed: int, fail_fraction: float = FAIL_FRACTION,
               placement_partitions: int = 0, handoff_partitions: int = 0):
    """The single definition of the warmed measurement (shared with
    experiments/scaling_sweep.py so the published sweep can never drift from
    the headline): compile on an identical-shape run, then time a fresh
    simulator from fault injection to the decided view, asserting cut-set
    parity. ``placement_partitions`` > 0 additionally enables the placement
    plane on the timed simulator (full map built before the clock starts;
    the timed window then includes the incremental in-view-change rebalance,
    which is the cost a placement-running deployment actually pays).
    ``handoff_partitions`` > 0 further enables the handoff plane (implying
    placement at that partition count if not already set): the diff's
    transfers execute store-to-store inside the view change, and the run
    asserts every session completed. Transfer time is billed on the
    simulator's virtual clock strictly after view_installed, so the
    stable-view distributions the bench pins are untouched.
    Returns (wall_ms, record, build_s, warmup_wall_s)."""
    from rapid_tpu.runtime import jitwatch
    from rapid_tpu.sim.driver import Simulator

    rng = np.random.default_rng(seed)
    n_fail = max(1, int(n_nodes * fail_fraction))

    js0 = jitwatch.stats()
    t_build0 = time.perf_counter()
    sim = Simulator(n_nodes, seed=seed)
    build_s = time.perf_counter() - t_build0

    victims = rng.choice(n_nodes, size=n_fail, replace=False)
    sim.crash(victims)
    warm = sim.run_until_decision(max_rounds=16, batch=16)
    assert warm is not None and set(warm.cut) == set(victims), "warmup parity failed"
    warm_wall = warm.wall_time_s

    sim2 = Simulator(n_nodes, seed=seed + 4444)
    sim2.ready()  # drain construction from the device queue
    if placement_partitions or handoff_partitions:
        sim2.enable_placement(
            partitions=placement_partitions or handoff_partitions
        )
    if handoff_partitions:
        sim2.enable_handoff()
    victims2 = rng.choice(n_nodes, size=n_fail, replace=False)
    sim2.crash(victims2)
    js1 = jitwatch.stats()
    t0 = time.perf_counter()
    if placement_partitions or handoff_partitions:
        # the in-view-change rebalance/handoff kernels warm lazily on their
        # first decision, so these points measure without a strict window
        record = sim2.run_until_decision(max_rounds=16, batch=16)
    else:
        # headline-compatible point: ANY compile or implicit host transfer
        # inside the timed region fails the bench rather than padding it
        with jitwatch.timed_window("bench.steady_state"):
            record = sim2.run_until_decision(max_rounds=16, batch=16)
    wall_ms = (time.perf_counter() - t0) * 1000.0
    js2 = jitwatch.stats()
    _LAST_JIT_STATS.clear()
    _LAST_JIT_STATS.update({
        "jit_compiles_warmup": js1["compiles"] - js0["compiles"],
        "jit_compile_ms_warmup": round(
            (js1["compile_wall_s"] - js0["compile_wall_s"]) * 1000.0, 1
        ),
        "jit_compiles_steady": js2["compiles"] - js1["compiles"],
        "jit_compile_ms_steady": round(
            (js2["compile_wall_s"] - js1["compile_wall_s"]) * 1000.0, 1
        ),
    })

    assert record is not None, "no decision reached"
    assert set(record.cut) == set(victims2), "cut-set parity violated"
    assert record.membership_size == n_nodes - len(victims2)
    if placement_partitions or handoff_partitions:
        diffs = sim2.placement_diffs
        assert diffs, "placement enabled but no rebalance happened"
        # minimal motion: every moved partition lost a replica to the cut
        partitions = placement_partitions or handoff_partitions
        assert all(d.moved <= partitions for d in diffs)
    if handoff_partitions:
        assert sim2.handoff_transfers, "handoff enabled but nothing moved"
        started = sim2.metrics.get("handoff.sessions_started")
        completed = sim2.metrics.get("handoff.sessions_completed")
        assert started > 0 and completed == started, (
            f"handoff sessions incomplete: {completed}/{started}"
        )
    return wall_ms, record, build_s, warm_wall


def run_sweep(backend: str, seed: int) -> list:
    """Warmed scaling curve. Each size is independent: a failure at one
    size is recorded as an error entry, not a lost artifact. Entries land in
    _PROGRESS["sweep"] as they complete so the watchdog can emit a partial
    curve."""
    sizes = [1_000, 10_000, 1_000_000] if backend == "tpu" else [1_000, 10_000]
    # placement + handoff ride along on the small sizes only: they exercise
    # the in-view-change rebalance and the diff-driven state transfers (and
    # feed the partitions-moved / session-bytes histograms in the JSON line)
    # without perturbing the headline-compatible big points
    placement_sizes = {1_000, 10_000}
    out = _PROGRESS["sweep"] = []
    for n in sizes:
        partitions = 1024 if n in placement_sizes else 0
        try:
            completed_before = _handoff_completed()
            wall_ms, record, _, _ = warmed_run(
                n, seed=seed, placement_partitions=partitions,
                handoff_partitions=partitions,
            )
            entry = {
                "n": n,
                "warmed_wall_ms": round(wall_ms, 1),
                "virtual_ms": record.virtual_time_ms,
                "cut_ok": True,  # asserted inside warmed_run
                "placement_partitions": partitions,
                **dict(_LAST_JIT_STATS),
            }
            if partitions:
                moved = _handoff_completed() - completed_before
                entry["handoff_partitions"] = moved
                entry["handoff_partitions_per_s"] = (
                    round(moved / (wall_ms / 1000.0), 1) if wall_ms > 0 else None
                )
            out.append(entry)
        except AssertionError:
            # a parity/correctness failure is a BUG, not a lost data point:
            # it must crash the bench (generic nonzero rc per the contract),
            # never be downgraded to an error entry in a rc-0 artifact
            raise
        except Exception as exc:  # noqa: BLE001 -- keep the rest of the curve
            out.append({"n": n, "error": f"{type(exc).__name__}: {exc}"})
            print(f"bench.py: sweep n={n} failed: {exc}", file=sys.stderr, flush=True)
    # the WAN dimension rides inside the sweep stage (the contract tests
    # stub run_sweep, so their stubbed runs skip the real simulators here);
    # an AssertionError is a parity bug and crashes per the rc contract
    try:
        run_wan_dimension(seed)
    except AssertionError:
        raise
    except Exception as exc:  # noqa: BLE001 -- keep the artifact
        _PROGRESS["wan"] = [{"error": f"{type(exc).__name__}: {exc}"}]
        print(f"bench.py: WAN dimension failed: {exc}", file=sys.stderr,
              flush=True)
    # serving dimension: same ride-along policy as WAN -- a lost-acked-write
    # is a correctness bug and crashes; anything else keeps the artifact
    try:
        run_serving_dimension(seed)
    except AssertionError:
        raise
    except Exception as exc:  # noqa: BLE001 -- keep the artifact
        _PROGRESS["serving"] = {"error": f"{type(exc).__name__}: {exc}"}
        print(f"bench.py: serving dimension failed: {exc}", file=sys.stderr,
              flush=True)
    # messaging dimension: real-socket transport throughput (loopback pair,
    # broadcast storm, thread-per-message A/B baseline); same ride-along
    # policy -- a stalled delivery keeps the artifact with an error entry
    try:
        run_messaging_dimension(seed)
    except AssertionError:
        raise
    except Exception as exc:  # noqa: BLE001 -- keep the artifact
        _PROGRESS["messaging"] = {"error": f"{type(exc).__name__}: {exc}"}
        print(f"bench.py: messaging dimension failed: {exc}", file=sys.stderr,
              flush=True)
    # gray-detection dimension: adaptive-vs-static FD A/B on the simulator;
    # a sub-2x speedup or broken cut parity is a regression and crashes
    try:
        run_gray_detection_dimension(seed)
    except AssertionError:
        raise
    except Exception as exc:  # noqa: BLE001 -- keep the artifact
        _PROGRESS["gray_detection"] = {"error": f"{type(exc).__name__}: {exc}"}
        print(f"bench.py: gray-detection dimension failed: {exc}",
              file=sys.stderr, flush=True)
    # recovery dimension: durability-plane cold-start replay; a wrong
    # replayed-record count or non-identical recovered content is a
    # correctness bug and crashes, anything else keeps the artifact
    try:
        run_recovery_dimension(seed)
    except AssertionError:
        raise
    except Exception as exc:  # noqa: BLE001 -- keep the artifact
        _PROGRESS["recovery"] = {"error": f"{type(exc).__name__}: {exc}"}
        print(f"bench.py: recovery dimension failed: {exc}",
              file=sys.stderr, flush=True)
    # hierarchy dimension: the flat-vs-hierarchical scale A/B; a parity or
    # composition-agreement failure is a correctness bug and crashes
    try:
        run_hierarchy_dimension(seed)
    except AssertionError:
        raise
    except Exception as exc:  # noqa: BLE001 -- keep the artifact
        _PROGRESS["hierarchy"] = {"error": f"{type(exc).__name__}: {exc}"}
        print(f"bench.py: hierarchy dimension failed: {exc}",
              file=sys.stderr, flush=True)
    return out


def run_wan_dimension(seed: int) -> list:
    """The WAN curve: warmed-style stable-view measurement at each
    inter-region RTT in WAN_RTTS_MS, identical crash workload, identical
    SimConfig shape (one jit cache entry serves all points). Cut parity is
    asserted at every point, same policy as the sweep. Entries land in
    _PROGRESS["wan"] as they complete so the watchdog can emit a partial
    curve."""
    from rapid_tpu.faults import apply_topology
    from rapid_tpu.sim.driver import Simulator
    from rapid_tpu.sim.engine import SimConfig
    from rapid_tpu.sim.topology import LatencyTopology

    n = WAN_N_NODES
    out = _PROGRESS["wan"] = []
    rng = np.random.default_rng(seed)
    for rtt in WAN_RTTS_MS:
        # one victim draw per RTT point: the flat measurement and the
        # hierarchical (region = cell) leg replay the identical workload,
        # so hier_virtual_ms is a same-seed cross-region agreement latency
        victims = rng.choice(n, size=n // 100, replace=False)
        entry = {"inter_region_rtt_ms": rtt, "n": n}
        for prefix, hierarchical in (("", False), ("hier_", True)):
            config = SimConfig(capacity=n, groups=2, max_delivery_delay=2,
                               rounds_per_interval=4)
            sim = Simulator(n, config=config, seed=seed)
            topo = None
            if rtt:
                topo = LatencyTopology(racks=2, zones=2, regions=2,
                                       rack_rtt_ms=0, zone_rtt_ms=0,
                                       region_rtt_ms=0,
                                       inter_region_rtt_ms=rtt)
                apply_topology(sim, topo)
            if hierarchical:
                # zone-aligned cells when a topology is present (one cell
                # per region); rendezvous split at the control point
                sim.enable_hierarchy(
                    cells=2, topology=topo,
                    parent_round_ms=HIER_PARENT_ROUND_MS,
                )
            sim.crash(victims)
            t0 = time.perf_counter()
            record = sim.run_until_decision(max_rounds=64, batch=16)
            wall_ms = (time.perf_counter() - t0) * 1000.0
            assert record is not None, f"no decision at inter-region RTT {rtt}"
            assert set(record.cut) == set(victims), (
                f"cut-set parity violated at inter-region RTT {rtt}"
            )
            entry[prefix + "virtual_ms"] = record.virtual_time_ms
            entry[prefix + "wall_ms"] = round(wall_ms, 1)
            if hierarchical:
                entry["hier_parent_rounds"] = sim.parent_rounds
        out.append(entry)
    return out


def run_hierarchy_dimension(seed: int) -> dict:
    """Flat vs hierarchical A/B on the same seed: the flat anchor runs
    HIER_FLAT_N members in one configuration; the hierarchical leg seats
    HIER_SCALE_FACTOR times as many across HIER_CELLS cells and must
    converge the same 1% correlated crash with cut parity, a composed
    global view that matches a from-scratch recompute, and at least one
    parent round billed on the virtual clock. member_ceiling_ratio is the
    scale claim the perfscope budget table gates (>= 10x)."""
    from rapid_tpu.sim.driver import Simulator
    from rapid_tpu.sim.engine import SimConfig

    out = _PROGRESS["hierarchy"] = {}

    def leg(n: int, cells: int) -> dict:
        config = SimConfig(capacity=n, rounds_per_interval=4)
        sim = Simulator(n, config=config, seed=seed)
        if cells:
            sim.enable_hierarchy(cells=cells,
                                 parent_round_ms=HIER_PARENT_ROUND_MS)
        rng = np.random.default_rng(seed)  # same draw for both legs' n-th
        victims = rng.choice(n, size=max(1, n // 100), replace=False)
        sim.crash(victims)
        t0 = time.perf_counter()
        record = sim.run_until_decision(max_rounds=64, batch=16)
        wall_ms = (time.perf_counter() - t0) * 1000.0
        assert record is not None, f"no decision at n={n} cells={cells}"
        assert set(record.cut) == set(victims), (
            f"cut-set parity violated at n={n} cells={cells}"
        )
        entry = {
            "n": n,
            "virtual_ms": record.virtual_time_ms,
            "wall_ms": round(wall_ms, 1),
            "cut_ok": True,
        }
        if cells:
            rows = sim.hierarchy_rows()
            composed = sim.global_fingerprint()
            for cell in range(cells):
                sim._hierarchy_recompute_cell(cell)  # noqa: SLF001
            assert sim.global_fingerprint() == composed, (
                "incremental composition diverged from recompute"
            )
            entry.update({
                "cells": cells,
                "live_cells": len(rows),
                "parent_rounds": sim.parent_rounds,
                "fingerprint_ok": True,
            })
        return entry

    flat = leg(HIER_FLAT_N, 0)
    hier = leg(HIER_FLAT_N * HIER_SCALE_FACTOR, HIER_CELLS)
    ratio = hier["n"] / flat["n"]
    assert ratio >= 10.0, f"hierarchical leg seats only {ratio:.1f}x"
    assert hier["parent_rounds"] >= 1, "no parent round billed"
    out.update({
        "cells": HIER_CELLS,
        "flat": flat,
        "hierarchical": hier,
        "member_ceiling_ratio": round(ratio, 1),
        "agreement_virtual_ms": hier["virtual_ms"],
    })
    return out


def _latency_window(latencies: list) -> dict:
    """Quantiles + full histogram for one measurement window, bucketed on
    the same SERVING_LATENCY_BUCKETS_MS ladder the engines observe into."""
    from rapid_tpu.observability import SERVING_LATENCY_BUCKETS_MS

    ordered = sorted(latencies)

    def pct(p: float) -> "float | None":
        if not ordered:
            return None
        return float(ordered[min(len(ordered) - 1, int(p * len(ordered)))])

    buckets = {
        str(b): sum(1 for x in ordered if x <= b)
        for b in SERVING_LATENCY_BUCKETS_MS
    }
    buckets["inf"] = len(ordered)
    return {
        "count": len(ordered),
        "p50_ms": pct(0.50),
        "p99_ms": pct(0.99),
        "latency_hist_ms": buckets,
    }


def run_serving_dimension(seed: int) -> dict:
    """The serving curve: an open-loop arrival stream (rate-scheduled,
    zipfian keys, completions never gate arrivals) drives Get/Put traffic
    against the simulator's serving plane across three windows -- steady
    state, the churn window between a crash and the decided view, and
    post-view. Latency is the virtual-ms span from *scheduled arrival* to
    completion, so queueing delay during churn is measured instead of
    silently omitted, and the entire dimension is deterministic per seed.
    The SLO plane rides the same stream; its summary (availability, p99,
    goodput, burn-rate peaks) folds into the JSON entry.
    Zero-lost-acked-writes is asserted after the view change: every write
    the oracle recorded as acknowledged must read back at >= its acked
    version."""
    from rapid_tpu.settings import SLOSettings
    from rapid_tpu.sim.driver import Simulator
    from rapid_tpu.slo import OpenLoopGenerator

    rng = np.random.default_rng(seed)
    sim = Simulator(SERVING_N_NODES, seed=seed)
    sim.enable_placement(partitions=SERVING_PARTITIONS)
    sim.enable_handoff()
    sim.enable_serving()
    plane = sim.enable_slo(SLOSettings(
        enabled=True, window_scale=SERVING_SLO_WINDOW_SCALE,
    ))
    keys = [b"bench-key-%04d" % i for i in range(SERVING_KEYS)]
    for i, key in enumerate(keys):  # preload, unmeasured
        ack = sim.serving_put(key, b"seed-%d" % i)
        assert ack.status == ack.STATUS_OK, "preload write failed to ack"
    gen = OpenLoopGenerator(
        SERVING_RATE_PER_S, keys, put_fraction=SERVING_PUT_FRACTION,
        seed=seed, zipf_s=SERVING_ZIPF_S, clients=SERVING_CLIENTS,
    )

    def drive(n_ops: int) -> "tuple[list, float]":
        # rebase forward so time the *harness* spent (preload, the decision
        # loop) is not billed to the clients as queueing delay
        gen.rebase(sim.virtual_ms)
        t0 = sim.virtual_ms
        results = sim.serving_drive_open_loop(gen.arrivals(n_ops))
        elapsed = float(max(sim.virtual_ms - t0, 1))
        return [lat for _a, _s, lat in results], elapsed

    windows, elapsed_ms = {}, {}
    windows["steady"], elapsed_ms["steady"] = drive(SERVING_OPS["steady"])
    victim = int(rng.integers(1, SERVING_N_NODES))
    sim.crash(np.array([victim]))
    windows["view_change_window"], elapsed_ms["view_change_window"] = drive(
        SERVING_OPS["view_change_window"]
    )
    record = sim.run_until_decision(max_rounds=64, batch=16)
    assert record is not None, "serving dimension: no view decision"
    assert set(record.cut) == {victim}, "serving dimension: cut parity"
    windows["post_view"], elapsed_ms["post_view"] = drive(
        SERVING_OPS["post_view"]
    )

    lost = 0
    for key, (version, value) in sim.serving_acked.items():
        back = sim.serving_get(key)
        if back.status != back.STATUS_OK or back.version < version:
            lost += 1
    assert lost == 0, f"serving dimension: {lost} acked writes lost"

    entry = {
        "n": SERVING_N_NODES,
        "partitions": SERVING_PARTITIONS,
        "put_fraction": SERVING_PUT_FRACTION,
        "offered_rate_per_s": SERVING_RATE_PER_S,
        "lost_acked_writes": 0,
        "virtual_ms": sim.virtual_ms,
    }
    total_ops, total_ms = 0, 0.0
    for name, latencies in windows.items():
        stats = _latency_window(latencies)
        stats["qps"] = round(
            1000.0 * len(latencies) / elapsed_ms[name], 1
        )
        entry[name] = stats
        total_ops += len(latencies)
        total_ms += elapsed_ms[name]
    entry["throughput_qps"] = (
        round(1000.0 * total_ops / total_ms, 1) if total_ms else None
    )
    entry["slo"] = plane.summary(sim.virtual_ms)
    _PROGRESS["serving"] = entry
    return entry


def run_gray_detection_dimension(seed: int) -> dict:
    """Detection->decision latency of a gray fault, adaptive vs static, on
    the simulator: identical WAN-shaped cluster, identical slow-node plan,
    the only difference SimConfig.fd_gray_confirm (the sim mirror of
    Settings.adaptive_fd). Detection is measured from the fault window
    opening to the decided view change, on virtual time, so every number is
    deterministic per seed. Cut parity (exactly the faulted node) and a
    >= 2x adaptive speedup are asserted for both fault shapes."""
    from rapid_tpu.faults import (
        FaultPlan,
        endpoint_slots,
        replay_on_simulator,
    )
    from rapid_tpu.sim.driver import Simulator
    from rapid_tpu.sim.engine import SimConfig
    from rapid_tpu.sim.topology import LatencyTopology

    n = GRAY_N_NODES
    entry: dict = {"n": n, "delay_ms": GRAY_DELAY_MS}
    topo = LatencyTopology(racks=4, zones=2, regions=2, rack_rtt_ms=0,
                           zone_rtt_ms=0, region_rtt_ms=0,
                           inter_region_rtt_ms=200)
    for scenario, windows in GRAY_WINDOWS.items():
        fault_open_ms = windows[0][0]
        detect = {}
        for mode, confirm in (("static", 0), ("adaptive", GRAY_CONFIRM)):
            config = SimConfig(capacity=n, groups=2, max_delivery_delay=2,
                               fd_gray_confirm=confirm,
                               fd_gray_warmup=GRAY_WARMUP)
            sim = Simulator(n, config=config, seed=seed)
            endpoint_of = {
                slot: ep for ep, slot in endpoint_slots(sim).items()
            }
            victim_slot = n - 1
            plan = FaultPlan(seed=seed).slow_node(
                endpoint_of[victim_slot], GRAY_DELAY_MS, windows=windows
            ).with_topology(topo)
            epoch = sim.virtual_ms
            records = replay_on_simulator(sim, plan, duration_ms=45_000)
            assert records, f"{scenario}/{mode}: no decision"
            assert [int(c) for c in records[0].cut] == [victim_slot], (
                f"{scenario}/{mode}: cut parity violated"
            )
            detect[mode] = (
                records[0].virtual_time_ms - epoch - fault_open_ms
            )
        speedup = detect["static"] / max(detect["adaptive"], 1)
        assert speedup >= 2.0, (
            f"{scenario}: adaptive detection {detect['adaptive']} ms is "
            f"under 2x faster than static {detect['static']} ms"
        )
        entry[scenario] = {
            "static_ms": int(detect["static"]),
            "adaptive_ms": int(detect["adaptive"]),
            "speedup": round(speedup, 2),
        }
    _PROGRESS["gray_detection"] = entry
    return entry


def run_recovery_dimension(seed: int) -> dict:
    """Cold-start recovery curve of the durability plane: seeded workloads
    of RECOVERY_LOG_RECORDS appends against a DurablePartitionStore at each
    snapshot cadence in RECOVERY_SNAPSHOT_EVERY, crashed abruptly (torn
    handle, no clean close) and reopened while the constructor replays
    log-over-snapshot. The replayed-record count is exact -- records since
    the last auto-checkpoint -- and the recovered content must be
    byte-identical to a shadow map of everything written; both are asserted.
    The wall number (recovery_ms per point) is the artifact."""
    import tempfile

    from rapid_tpu.durability import FSYNC_NEVER, DurablePartitionStore

    points = []
    for every in RECOVERY_SNAPSHOT_EVERY:
        for records in RECOVERY_LOG_RECORDS:
            rng = np.random.default_rng(seed * 7919 + records * 31 + every)
            with tempfile.TemporaryDirectory(
                prefix="rapid-bench-recovery-"
            ) as directory:
                store = DurablePartitionStore(
                    directory, fsync_policy=FSYNC_NEVER,
                    snapshot_every_records=every,
                )
                shadow = {}
                for i in range(records):
                    p = int(rng.integers(RECOVERY_PARTITIONS))
                    value = b"%08d-" % i + bytes(
                        rng.integers(0, 256, RECOVERY_VALUE_BYTES, dtype=np.uint8)
                    )
                    store.put(p, value)
                    shadow[p] = value
                store.crash()  # power loss: no flush, no snapshot marker
                t0 = time.perf_counter()
                reopened = DurablePartitionStore(
                    directory, fsync_policy=FSYNC_NEVER,
                    snapshot_every_records=every,
                )
                wall_ms = (time.perf_counter() - t0) * 1000.0
                stats = reopened.durability_stats()
                expected = records % every if every else records
                assert stats["replayed_records"] == expected, (
                    f"recovery dimension: replayed {stats['replayed_records']}"
                    f" records, expected {expected} "
                    f"(log={records}, snapshot_every={every})"
                )
                recovered = {
                    p: reopened.get(p) for p in reopened.partitions()
                }
                assert recovered == shadow, (
                    "recovery dimension: recovered content diverged from "
                    "the written state"
                )
                reopened.close()
                points.append({
                    "log_records": records,
                    "snapshot_every": every,
                    "replayed_records": int(stats["replayed_records"]),
                    "segments": int(stats["segments"]),
                    "recovery_ms": round(wall_ms, 2),
                })
    entry = {
        "partitions": RECOVERY_PARTITIONS,
        "value_bytes": RECOVERY_VALUE_BYTES,
        "points": points,
    }
    _PROGRESS["recovery"] = entry
    return entry


def _messaging_rate(count: int, wall_s: float, nbytes: float = 0.0) -> dict:
    return {
        "messages": count,
        "wall_ms": round(wall_s * 1000.0, 1),
        "messages_per_s": round(count / wall_s, 1) if wall_s > 0 else None,
        "bytes_per_s": round(nbytes / wall_s, 1) if wall_s > 0 else None,
    }


def _messaging_loopback_pair() -> dict:
    """Pipelined RPC round-trips over one loopback connection: every probe
    is answered (the transport's built-in BOOTSTRAPPING responder), so the
    rate includes framing, codec, dispatch, and the response path."""
    from rapid_tpu.messaging.ports import free_port
    from rapid_tpu.settings import Settings
    from rapid_tpu.types import Endpoint, ProbeMessage, ProbeResponse

    from rapid_tpu.messaging.tcp import TcpClientServer

    settings = Settings(message_timeout_ms=int(MESSAGING_DEADLINE_S * 1000))
    server = TcpClientServer(
        Endpoint.from_parts("127.0.0.1", free_port()), settings
    )
    server.start()
    client = TcpClientServer(Endpoint.from_parts("127.0.0.1", 0), settings)
    me = client.address
    try:
        probe = ProbeMessage(sender=me)
        # warm the dial + first flush before the timed window
        assert isinstance(
            client.send_message_best_effort(
                server.address, probe
            ).result(MESSAGING_DEADLINE_S),
            ProbeResponse,
        )
        t0 = time.perf_counter()
        promises = [
            client.send_message_best_effort(server.address, probe)
            for _ in range(MESSAGING_PAIR_MSGS)
        ]
        for p in promises:
            p.result(MESSAGING_DEADLINE_S)
        wall_s = time.perf_counter() - t0
        sent = client.metrics.snapshot()
        return {
            **_messaging_rate(
                MESSAGING_PAIR_MSGS, wall_s, sent.get("msg.bytes_sent", 0)
            ),
            "flush_syscalls_per_msg": round(
                sent.get("msg.flush_syscalls", 0)
                / max(1, sent.get("msg.sent", 0)),
                3,
            ),
        }
    finally:
        client.shutdown()
        server.shutdown()


def _messaging_reactor_storm() -> dict:
    """The broadcast storm on the event-loop transport: every node
    broadcasts BURST votes per round through the flush-window batching
    broadcaster, so per-peer traffic leaves as MessageBatch envelopes and
    the reactor coalesces whatever accumulates per tick into single
    writes. Counts are exact: the dimension waits until every inner
    message has been dispatched on its destination node."""
    from rapid_tpu.messaging.ports import free_port_base
    from rapid_tpu.messaging.tcp import TcpClientServer
    from rapid_tpu.messaging.unicast import UnicastToAllBroadcaster
    from rapid_tpu.messaging.retries import wall_scheduler
    from rapid_tpu.runtime.futures import Promise
    from rapid_tpu.settings import Settings
    from rapid_tpu.types import (
        Endpoint,
        FastRoundPhase2bMessage,
        MessageBatch,
        Response,
    )

    n = MESSAGING_STORM_NODES
    rounds, burst = MESSAGING_STORM_ROUNDS, MESSAGING_STORM_BURST
    settings = Settings(
        message_timeout_ms=int(MESSAGING_DEADLINE_S * 1000),
        broadcast_flush_window_ms=MESSAGING_FLUSH_WINDOW_MS,
    )
    base = free_port_base(n)
    addrs = [Endpoint.from_parts("127.0.0.1", base + i) for i in range(n)]
    received = threading.Semaphore(0)

    class _CountingService:
        """Destination-side sink: unwraps batch envelopes and releases one
        semaphore permit per inner vote."""

        def handle_message(self, msg):
            if isinstance(msg, MessageBatch):
                received.release(len(msg.messages))
            else:
                received.release()
            return Promise.completed(Response())

    nodes = []
    try:
        for addr in addrs:
            node = TcpClientServer(addr, settings)
            node.set_membership_service(_CountingService())
            node.start()
            nodes.append(node)
        casters = [
            UnicastToAllBroadcaster(
                node, settings=settings, scheduler=wall_scheduler(),
                my_addr=node.address,
            )
            for node in nodes
        ]
        for caster in casters:
            caster.set_membership(list(addrs))
        expected = n * (n - 1) * rounds * burst

        def drive(i):
            vote = FastRoundPhase2bMessage(
                sender=addrs[i], configuration_id=-1, endpoints=(addrs[i],)
            )
            for _ in range(rounds):
                for _ in range(burst):
                    casters[i].broadcast(vote)

        t0 = time.perf_counter()
        drivers = [
            threading.Thread(target=drive, args=(i,), daemon=True)
            for i in range(n)
        ]
        for t in drivers:
            t.start()
        for t in drivers:
            t.join(MESSAGING_DEADLINE_S)
        deadline = time.time() + MESSAGING_DEADLINE_S
        for _ in range(expected):
            if not received.acquire(timeout=max(0.0, deadline - time.time())):
                raise RuntimeError("storm delivery stalled")
        wall_s = time.perf_counter() - t0

        sent, syscalls, nbytes = 0, 0, 0
        for node in nodes:
            snap = node.metrics.snapshot()
            sent += snap.get("msg.sent", 0)
            syscalls += snap.get("msg.flush_syscalls", 0)
            nbytes += snap.get("msg.bytes_sent", 0)
        return {
            "n": n,
            "rounds": rounds,
            "burst": burst,
            **_messaging_rate(expected, wall_s, nbytes),
            "frames_sent": sent,
            "flush_syscalls": syscalls,
            "flush_syscalls_per_msg": round(syscalls / expected, 4),
        }
    finally:
        for node in nodes:
            node.shutdown()


def _messaging_threaded_baseline() -> dict:
    """The pre-event-loop transport shape, reproduced in-bench for the A/B
    numbers: a reader thread per accepted connection that decodes every
    frame and writes back a Response under the connection's write lock, a
    response-reader thread per outbound connection that decodes and matches
    replies against the per-node outstanding table, a Promise per request
    armed on the shared timeout-wheel heap (the old transport's
    ``_TimeoutWheel.arm``: heappush + notify under one condition, one
    scanning deadline thread), and one blocking ``sendall`` per message (so
    exactly one write syscall per message per direction, by construction).
    Same storm workload, same codec, same RPC bookkeeping -- minus the
    reactor, the coalescing, and the batch envelopes, which is precisely
    the A/B."""
    import heapq
    import itertools
    import socket as socket_mod

    from rapid_tpu.messaging.codec import HEADER, decode, encode
    from rapid_tpu.messaging.tcp import _read_frame
    from rapid_tpu.runtime.futures import Promise
    from rapid_tpu.types import Endpoint, FastRoundPhase2bMessage, Response

    n = MESSAGING_STORM_NODES
    rounds, burst = MESSAGING_STORM_ROUNDS, MESSAGING_STORM_BURST
    expected = n * (n - 1) * rounds * burst
    received = threading.Semaphore(0)
    listeners, socks = [], []

    # the pre-PR shared timeout wheel, verbatim shape: one heap, one
    # condition, one scanning deadline thread; arm() is a heappush + notify
    # per request, and completed promises simply expire off the heap
    wheel_heap: list = []
    wheel_seq = itertools.count()
    wheel_cond = threading.Condition()
    wheel_done = False

    def wheel_arm(timeout_s, promise):
        deadline = time.monotonic() + timeout_s
        with wheel_cond:
            heapq.heappush(wheel_heap, (deadline, next(wheel_seq), promise))
            wheel_cond.notify()

    def wheel_loop():
        while True:
            with wheel_cond:
                while not wheel_heap:
                    if wheel_done:
                        return
                    wheel_cond.wait()
                delay = wheel_heap[0][0] - time.monotonic()
                if delay > 0:
                    if wheel_done:
                        return
                    wheel_cond.wait(delay)
                    continue
                _, _, promise = heapq.heappop(wheel_heap)
            if not promise.done():
                promise.try_set_exception(TimeoutError("baseline timeout"))

    threading.Thread(target=wheel_loop, daemon=True).start()

    def server_reader(sock):
        """Pre-PR server half: decode, dispatch (counted), respond inline
        under the connection write lock."""
        wlock = threading.Lock()
        try:
            while True:
                frame = _read_frame(sock)
                if frame is None:
                    return
                request_no, _msg = decode(frame)
                received.release()
                resp = encode(request_no, Response())
                with wlock:
                    sock.sendall(HEADER.pack(len(resp)) + resp)
        except OSError:
            pass

    def acceptor(listener):
        try:
            while True:
                sock, _ = listener.accept()
                socks.append(sock)
                threading.Thread(
                    target=server_reader, args=(sock,), daemon=True
                ).start()
        except OSError:
            pass

    def response_reader(sock, outstanding, lock):
        """Pre-PR client half: match every reply against the outstanding
        table (the per-message bookkeeping the old reader threads did)."""
        try:
            while True:
                frame = _read_frame(sock)
                if frame is None:
                    return
                request_no, resp = decode(frame)
                with lock:
                    promise = outstanding.pop(request_no, None)
                if promise is not None:
                    promise.try_set_result(resp)
        except OSError:
            pass

    try:
        ports = []
        for _ in range(n):
            listener = socket_mod.socket()
            listener.bind(("127.0.0.1", 0))
            listener.listen(n)
            ports.append(listener.getsockname()[1])
            listeners.append(listener)
            threading.Thread(
                target=acceptor, args=(listener,), daemon=True
            ).start()
        # node i: one blocking socket + response reader per peer, dialed up
        # front; one outstanding table per node (as the old transport kept)
        peers, tables = [], []
        for i in range(n):
            row = []
            outstanding, lock = {}, threading.Lock()
            tables.append((outstanding, lock))
            for j in range(n):
                if j == i:
                    continue
                sock = socket_mod.create_connection(
                    ("127.0.0.1", ports[j]), timeout=MESSAGING_DEADLINE_S
                )
                sock.setsockopt(
                    socket_mod.IPPROTO_TCP, socket_mod.TCP_NODELAY, 1
                )
                socks.append(sock)
                threading.Thread(
                    target=response_reader, args=(sock, outstanding, lock),
                    daemon=True,
                ).start()
                row.append((sock, threading.Lock()))
            peers.append(row)

        def drive(i):
            vote = FastRoundPhase2bMessage(
                sender=Endpoint.from_parts("127.0.0.1", ports[i]),
                configuration_id=-1,
                endpoints=(Endpoint.from_parts("127.0.0.1", ports[i]),),
            )
            request_no = itertools.count()
            outstanding, lock = tables[i]
            for _ in range(rounds):
                for _ in range(burst):
                    for sock, wlock in peers[i]:
                        no_ = next(request_no)
                        frame = encode(no_, vote)
                        out = Promise()
                        with lock:
                            outstanding[no_] = out
                        with wlock:
                            # one write syscall per message, pre-PR style
                            sock.sendall(HEADER.pack(len(frame)) + frame)
                        wheel_arm(MESSAGING_DEADLINE_S, out)

        t0 = time.perf_counter()
        drivers = [
            threading.Thread(target=drive, args=(i,), daemon=True)
            for i in range(n)
        ]
        for t in drivers:
            t.start()
        for t in drivers:
            t.join(MESSAGING_DEADLINE_S)
        deadline = time.time() + MESSAGING_DEADLINE_S
        for _ in range(expected):
            if not received.acquire(timeout=max(0.0, deadline - time.time())):
                raise RuntimeError("baseline delivery stalled")
        wall_s = time.perf_counter() - t0
        vote = FastRoundPhase2bMessage(
            sender=Endpoint.from_parts("127.0.0.1", ports[0]),
            configuration_id=-1,
            endpoints=(Endpoint.from_parts("127.0.0.1", ports[0]),),
        )
        vote_wire = HEADER.size + len(encode(0, vote))
        return {
            **_messaging_rate(expected, wall_s, float(expected * vote_wire)),
            "flush_syscalls_per_msg": 1.0,  # by construction
        }
    finally:
        with wheel_cond:
            wheel_done = True
            wheel_heap.clear()
            wheel_cond.notify()
        for sock in socks:
            try:
                sock.close()
            except OSError:
                pass
        for listener in listeners:
            try:
                listener.close()
            except OSError:
                pass


def run_messaging_dimension(seed: int) -> dict:
    """The transport curve: loopback RPC round-trip rate, the 16-node
    broadcast storm on the event-loop core, and the thread-per-message
    baseline -- with the A/B speedup and write-syscall reduction that the
    event-loop refactor (reactor coalescing + MessageBatch envelopes)
    buys on storm traffic."""
    del seed  # workload is fixed; socket timing is inherently wall-clock
    entry = {
        "loopback_pair": _messaging_loopback_pair(),
        "broadcast_storm": _messaging_reactor_storm(),
        "threaded_baseline": _messaging_threaded_baseline(),
    }
    storm = entry["broadcast_storm"]
    baseline = entry["threaded_baseline"]
    if storm["messages_per_s"] and baseline["messages_per_s"]:
        entry["speedup_vs_threaded"] = round(
            storm["messages_per_s"] / baseline["messages_per_s"], 2
        )
    if storm["flush_syscalls_per_msg"]:
        entry["syscall_reduction_vs_threaded"] = round(
            baseline["flush_syscalls_per_msg"]
            / storm["flush_syscalls_per_msg"],
            1,
        )
    _PROGRESS["messaging"] = entry
    return entry


def main() -> None:
    _arm_watchdog()
    backend = probe_backend()
    if backend is None:
        _emit_outage_json(
            f"accelerator unreachable after {len(PROBE_TIMEOUTS_S)} probes"
        )
        print(
            "bench.py: accelerator unreachable after "
            f"{len(PROBE_TIMEOUTS_S)} bounded probes -- the TPU tunnel's "
            "upstream is down (known signature: connect to the relay "
            "succeeds, then immediate EOF; device init hangs forever). "
            "No measurement was produced. rc=17 means infrastructure "
            "outage, not regression.",
            file=sys.stderr,
            flush=True,
        )
        sys.exit(17)

    wall_ms, record, build_s, warm_wall = warmed_run(N_NODES, seed=1234)
    _PROGRESS["backend"] = backend
    _PROGRESS["headline"] = {
        "value": round(wall_ms, 1),
        "virtual_ms": record.virtual_time_ms,
        **dict(_LAST_JIT_STATS),
    }
    sweep = run_sweep(backend, seed=42)
    _emit_json(_PROGRESS["headline"], backend, sweep)
    _write_telemetry()
    print(
        f"# membership={N_NODES}->{record.membership_size} cut={len(record.cut)} nodes "
        f"virtual_time={record.virtual_time_ms}ms config_id={record.configuration_id} "
        f"build={build_s:.1f}s warmup_wall={warm_wall:.1f}s",
        file=sys.stderr,
    )
    if backend == "tpu" and wall_ms > TPU_BUDGET_MS:
        print(
            f"bench.py: warmed 100k wall {wall_ms:.1f} ms exceeds the "
            f"{TPU_BUDGET_MS:.0f} ms TPU budget -- structural perf "
            "regression (rc=18). The JSON above is still the measurement.",
            file=sys.stderr,
            flush=True,
        )
        sys.exit(18)


if __name__ == "__main__":
    main()
