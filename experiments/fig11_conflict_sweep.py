"""Reproduces the BASELINE.md 'Timing-induced consensus conflicts' table.

Grid: N=64, rounds_per_interval=10 (100 ms sub-rounds), two delivery
classes split even/odd, two crashed victims; class 1 hears victim A's
observers' alerts ``skew`` sub-rounds late (latency only -- nothing is
dropped). 18 trials per skew: seeds 0-5 x victim pairs {5,40}, {11,52},
{3,20}. A trial conflicts when the two classes announce unequal proposals;
every conflict is then driven through the classic fallback to convergence.

Run: python experiments/fig11_conflict_sweep.py   (~3 min on CPU jax)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from rapid_tpu.sim.driver import Simulator  # noqa: E402
from rapid_tpu.sim.engine import SimConfig  # noqa: E402

SEEDS = range(6)
VICTIM_PAIRS = ([5, 40], [11, 52], [3, 20])
SKEWS = (0, 2, 5, 9)
N = 64


def trial(seed, victims, skew):
    config = SimConfig(
        capacity=N, rounds_per_interval=10, groups=2,
        max_delivery_delay=max(skew, 1),
    )
    sim = Simulator(N, config=config, seed=seed)
    sim.set_delivery_groups((np.arange(N) % 2).astype(np.int32))
    victims = np.array(victims)
    sim.crash(victims)
    if skew:
        sim.delay_broadcasts(1, np.asarray(sim.state.observers)[victims[0]], skew)
    rec = sim.run_until_decision(
        max_rounds=200, batch=40, classic_fallback_after_rounds=None
    )
    conflict = False
    if sim.last_announcement is not None:
        announced, proposals = sim.last_announcement
        conflict = bool(
            announced[:2].all()
            and not np.array_equal(proposals[0], proposals[1])
        )
    converged = rec is not None
    if not converged:
        # drive the stalled conflict through the classic fallback
        while sim.membership_size != N - len(victims):
            follow = sim.run_until_decision(
                max_rounds=300, batch=50, classic_fallback_after_rounds=20
            )
            assert follow is not None, "fallback failed to converge"
        converged = True
    assert not sim.active[victims].any()
    return conflict, rec is None


def main():
    print(f"| latency skew (sub-rounds) | {' | '.join(map(str, SKEWS))} |")
    rows = {"conflict rate": [], "fast round stalled": []}
    for skew in SKEWS:
        conflicts = stalls = trials = 0
        for seed in SEEDS:
            for victims in VICTIM_PAIRS:
                c, stalled = trial(seed, victims, skew)
                trials += 1
                conflicts += c
                stalls += stalled
        rows["conflict rate"].append(f"{conflicts}/{trials}")
        rows["fast round stalled"].append(f"{stalls}/{trials}")
        print(f"skew {skew}: conflicts {conflicts}/{trials}, "
              f"stalls {stalls}/{trials}, all converged")
    for name, cells in rows.items():
        print(f"| {name} | {' | '.join(cells)} |")


if __name__ == "__main__":
    main()
