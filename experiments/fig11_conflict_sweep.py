"""Reproduces the BASELINE.md 'Timing-induced consensus conflicts' table.

Grid: N=64, rounds_per_interval=10 (100 ms sub-rounds), two delivery
classes split even/odd, two crashed victims; class 1 hears victim A's
observers' alerts ``skew`` sub-rounds late (latency only -- nothing is
dropped). 18 trials per skew: seeds 0-5 x victim pairs {5,40}, {11,52},
{3,20}. A trial conflicts when the two classes announce unequal proposals;
every conflict is then driven through the classic fallback to convergence.

``run_trial`` is the single definition of the regime -- the fast regression
(tests/test_timing_conflicts.py) imports it, so the published table and its
test can never desynchronize.

Run: python experiments/fig11_conflict_sweep.py   (~3 min on CPU jax)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if __name__ == "__main__":
    # force the CPU backend BEFORE any jax use: merely setting JAX_PLATFORMS
    # does not stop an injected accelerator plugin, and a dead remote-device
    # tunnel hangs device init forever (tests get this from conftest.py)
    from __graft_entry__ import _force_cpu_mesh

    _force_cpu_mesh(1)

import numpy as np  # noqa: E402

from rapid_tpu.sim.driver import Simulator  # noqa: E402
from rapid_tpu.sim.engine import SimConfig  # noqa: E402

SEEDS = range(6)
VICTIM_PAIRS = ([5, 40], [11, 52], [3, 20])
SKEWS = (0, 2, 5, 9)
N = 64


def run_trial(seed, victims, skew, n=N, rpi=10, fallback=None):
    """One scenario: two victims crash; delivery class 1 hears victim A's
    observers ``skew`` sub-rounds late. Returns (conflict, record, sim)."""
    config = SimConfig(
        capacity=n, rounds_per_interval=rpi, groups=2,
        max_delivery_delay=max(skew, 1),
    )
    sim = Simulator(n, config=config, seed=seed)
    sim.set_delivery_groups((np.arange(n) % 2).astype(np.int32))
    victims = np.array(victims)
    sim.crash(victims)
    if skew:
        obs_a = np.asarray(sim.state.observers)[victims[0]]
        sim.delay_broadcasts(1, obs_a, skew)
    rec = sim.run_until_decision(
        max_rounds=200, batch=40, classic_fallback_after_rounds=fallback
    )
    conflict = False
    if sim.last_announcement is not None:
        announced, proposals = sim.last_announcement
        conflict = bool(
            announced[:2].all()
            and not np.array_equal(proposals[0], proposals[1])
        )
    return conflict, rec, sim


def drive_to_convergence(sim, n_final, max_view_changes=3):
    """Classic-fallback recovery until membership is exactly ``n_final``;
    bounded so a protocol anomaly fails loudly instead of hanging."""
    for _ in range(max_view_changes):
        if sim.membership_size == n_final:
            return
        follow = sim.run_until_decision(
            max_rounds=300, batch=50, classic_fallback_after_rounds=20
        )
        assert follow is not None, "fallback failed to converge"
    assert sim.membership_size == n_final, (
        f"membership {sim.membership_size} != {n_final} after "
        f"{max_view_changes} view changes"
    )


def main():
    print(f"| latency skew (sub-rounds) | {' | '.join(map(str, SKEWS))} |")
    rows = {"conflict rate": [], "fast round stalled": []}
    for skew in SKEWS:
        conflicts = stalls = trials = 0
        for seed in SEEDS:
            for victims in VICTIM_PAIRS:
                conflict, rec, sim = run_trial(seed, victims, skew)
                trials += 1
                conflicts += conflict
                stalls += rec is None
                drive_to_convergence(sim, N - len(victims))
                assert not sim.active[np.array(victims)].any()
        rows["conflict rate"].append(f"{conflicts}/{trials}")
        rows["fast round stalled"].append(f"{stalls}/{trials}")
        print(f"skew {skew}: conflicts {conflicts}/{trials}, "
              f"stalls {stalls}/{trials}, all converged")
    for name, cells in rows.items():
        print(f"| {name} | {' | '.join(cells)} |")


if __name__ == "__main__":
    main()
