"""Warmed-wall scaling sweep: time-to-stable-view vs cluster size.

BASELINE.md's main table reports wall time including per-scenario jit
compilation; this sweep isolates the *warmed* decision cost -- what a
long-running deployment actually pays per view change -- across the scale
axis (SURVEY.md section 5.7: cluster size N is this framework's scale
dimension). One compile per capacity, then a fresh same-shape simulator is
timed from fault injection to the decided view, exactly like bench.py.

Run: python experiments/scaling_sweep.py            (real TPU or CPU)
     python experiments/scaling_sweep.py --sizes 1000,10000

Prints one JSON line per size:
  {"n", "fail_fraction", "warmed_wall_ms", "virtual_ms", "cut_ok"}
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import FAIL_FRACTION, warmed_run  # noqa: E402

DEFAULT_SIZES = (1_000, 10_000, 100_000, 1_000_000)


def run_size(n: int, seed: int) -> dict:
    """One measurement through bench.py's warmed_run -- the single
    definition of the warmed harness, so this sweep can never drift from
    the headline benchmark. warmed_run asserts cut-set parity internally
    (an inexact cut raises rather than printing cut_ok: false)."""
    wall_ms, record, _build_s, _warm_wall = warmed_run(n, seed=seed)
    return {
        "n": n,
        "fail_fraction": FAIL_FRACTION,
        "warmed_wall_ms": round(wall_ms, 1),
        "virtual_ms": record.virtual_time_ms,
        "cut_ok": True,  # asserted by warmed_run before returning
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--sizes", default=",".join(str(s) for s in DEFAULT_SIZES),
        help="comma-separated cluster sizes",
    )
    parser.add_argument("--seed", type=int, default=42)
    args = parser.parse_args()
    for n in (int(s) for s in args.sizes.split(",")):
        print(json.dumps(run_size(n, args.seed)), flush=True)


if __name__ == "__main__":
    main()
