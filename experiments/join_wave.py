"""Join-wave (bootstrap/elasticity) sweep: time-to-stable-view for a burst
of joiners entering an established cluster, across the scale axis.

The paper's bootstrap headline (Fig. 5: N=2000 bootstraps 2-5.8x faster
than ZooKeeper/Memberlist because joins batch into few view changes) has
this analogue here: a wave of W joiners lands in one configuration, their
UP alerts aggregate through the same H/L cut detection as failures, and the
whole wave is admitted in a single fast-round decision (join is a cut of
adds -- MembershipService.java:229-286; the sim plane arms join reports for
every pending joiner each configuration).

One compile per capacity, then a fresh same-shape simulator is timed from
wave arrival to the decided view that admits every joiner.

Run: python experiments/join_wave.py
     python experiments/join_wave.py --sizes 1000,10000 --wave 100

Prints one JSON line per size:
  {"n", "wave", "warmed_wall_ms", "virtual_ms", "admitted_ok"}
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rapid_tpu.sim.driver import Simulator  # noqa: E402

DEFAULT_SIZES = (1_000, 10_000, 100_000)
DEFAULT_WAVE = 0.01  # joiners as a fraction of N


def timed_wave(n: int, wave: int, seed: int):
    """(wall_ms, record) for a W-joiner wave into an N-member cluster."""
    sim = Simulator(n, capacity=n + wave, seed=seed)
    sim.ready()
    joiners = np.arange(n, n + wave)
    sim.request_joins(joiners)
    t0 = time.perf_counter()
    record = sim.run_until_decision(max_rounds=16, batch=16)
    wall_ms = (time.perf_counter() - t0) * 1000.0
    assert record is not None, "wave not admitted in budget"
    assert set(record.added) == set(int(j) for j in joiners), "partial admission"
    assert record.membership_size == n + wave
    return wall_ms, record


def run_size(n: int, wave_frac_or_count, seed: int) -> dict:
    wave = (
        int(wave_frac_or_count)
        if wave_frac_or_count >= 1
        else max(1, int(n * wave_frac_or_count))
    )
    # warm the executable on an identical-shape run, then measure fresh
    timed_wave(n, wave, seed)
    wall_ms, record = timed_wave(n, wave, seed + 4444)
    return {
        "n": n,
        "wave": wave,
        "warmed_wall_ms": round(wall_ms, 1),
        "virtual_ms": record.virtual_time_ms,
        "admitted_ok": True,  # asserted in timed_wave
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--sizes", default=",".join(str(s) for s in DEFAULT_SIZES),
        help="comma-separated cluster sizes",
    )
    parser.add_argument(
        "--wave", type=float, default=DEFAULT_WAVE,
        help="joiner count (>=1) or fraction of N (<1)",
    )
    parser.add_argument("--seed", type=int, default=42)
    args = parser.parse_args()
    for n in (int(s) for s in args.sizes.split(",")):
        print(json.dumps(run_size(n, args.wave, args.seed)), flush=True)


if __name__ == "__main__":
    main()
