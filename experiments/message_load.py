"""Per-process message load during a crash experiment: unicast vs gossip.

The paper's Table 2 reports per-process network utilization for a
1000-process crash experiment (Rapid's headline there: mean AND p99 stay
low, unlike ZooKeeper's coordinator-skewed p99). This experiment reproduces
the shape of that measurement on the in-process virtual-time cluster: run an
N-node cluster, crash a few members, converge, and report the distribution
of protocol messages RECEIVED per process (the service's per-type counters)
under each dissemination strategy.

What it shows, concretely: with unicast-to-all every node receives each
broadcast exactly once (the origin pays the whole O(N) send burst); with
gossip every node receives ~fanout x relay_budget copies (the epidemic
redundancy factor -- measured ~8.6x at N=32, fanout=4, budget=2) while any
process's sends per broadcast are bounded by fanout+1 initial sends at the
origin plus relay_budget x fanout relays (13 at the defaults) -- constant
in N, where unicast's origin burst grows linearly.
The per-type totals pin that the PROTOCOL work (alert batches delivered,
votes tallied) is identical under both strategies -- only the
dissemination fabric differs. Run:

    python experiments/message_load.py            (defaults: N=32, crash 2)
    python experiments/message_load.py --n 50 --crash 3

Prints one JSON line per strategy:
  {"strategy", "n", "crashed", "mean_msgs", "p50", "p99", "max",
   "per_type_totals"}
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests")
)


def run_strategy(strategy: str, n: int, crash: int, seed: int,
                 failure_mode: str = "crash") -> dict:
    from harness import ClusterHarness

    # crash mode uses the instant static FDs (the paper's Table 2 shape:
    # dissemination cost of one clean cut). one-way mode uses the REAL
    # cumulative PingPong detectors -- detection must flow through actual
    # probe loss across the asymmetric fault, so the column measures the
    # dissemination fabric under the noisier probe-driven alert pattern
    h = ClusterHarness(seed=seed, use_static_fd=(failure_mode == "crash"))
    if strategy.startswith("gossip"):
        from rapid_tpu.messaging.gossip import GossipBroadcaster

        mode = "pushpull" if strategy == "gossip-pushpull" else "eager"
        h.broadcaster_factory = lambda client, rng: GossipBroadcaster(
            client, client.address, fanout=4, rng=rng, mode=mode
        )
    try:
        return _measure(h, strategy, n, crash, failure_mode)
    finally:
        h.shutdown()


def _measure(h, strategy: str, n: int, crash: int,
             failure_mode: str = "crash") -> dict:
    h.create_cluster(n, parallel=False)
    h.wait_and_verify_agreement(n)
    # zero the counters after bootstrap so the measurement is the failure
    # experiment itself, like the paper's steady-state window
    for inst in h.instances.values():
        inst._membership_service.metrics.reset()  # noqa: SLF001
    victims = [h.addr(i) for i in range(2, 2 + crash)]
    if failure_mode == "crash":
        h.fail_nodes(victims)
    elif failure_mode == "one-way":
        # paper Fig. 9's iptables INPUT shape: victims receive nothing,
        # their egress still flows; the survivors' PingPong detectors
        # accumulate real probe losses until the alert threshold
        victim_set = set(victims)
        h.network.add_filter(lambda s, d, m: d not in victim_set)
    else:
        raise ValueError(f"unknown failure mode {failure_mode}")
    survivors = [
        c for ep, c in h.instances.items() if ep not in set(victims)
    ]
    ok = h.scheduler.run_until(
        lambda: all(
            len(c.get_memberlist()) == n - crash for c in survivors
        ),
        timeout_ms=600_000,
    )
    assert ok, "survivors did not converge"
    for v in victims:
        c = h.instances.pop(v, None)
        if c is not None and failure_mode != "crash":
            c.shutdown()

    per_process = []
    per_process_control = []  # payload-free IHAVE/PULL frames (pushpull)
    per_type: dict = {}
    for inst in h.instances.values():
        snap = inst._membership_service.metrics.snapshot()  # noqa: SLF001
        total = sum(
            v for k, v in snap.items()
            if k.startswith("messages.") and not k.endswith(".control")
        )
        per_process.append(total)
        per_process_control.append(
            sum(v for k, v in snap.items() if k.endswith(".control"))
        )
        for k, v in snap.items():
            if k.startswith("messages."):
                per_type[k[len("messages."):]] = per_type.get(k[len("messages."):], 0) + v
    arr = np.array(per_process)
    ctl = np.array(per_process_control)
    return {
        "strategy": strategy,
        "failure_mode": failure_mode,
        "n": n,
        "crashed": crash,
        "mean_msgs": round(float(arr.mean()), 1),
        "p50": int(np.percentile(arr, 50)),
        "p99": int(np.percentile(arr, 99)),
        "max": int(arr.max()),
        "mean_control": round(float(ctl.mean()), 1),
        "per_type_totals": dict(sorted(per_type.items())),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=32)
    parser.add_argument("--crash", type=int, default=2)
    parser.add_argument("--seed", type=int, default=11)
    parser.add_argument("--failure-mode", default="crash",
                        choices=("crash", "one-way", "all"))
    args = parser.parse_args()
    modes = (
        ("crash", "one-way")
        if args.failure_mode == "all"
        else (args.failure_mode,)
    )
    for failure_mode in modes:
        for strategy in ("unicast", "gossip", "gossip-pushpull"):
            print(
                json.dumps(run_strategy(
                    strategy, args.n, args.crash, args.seed,
                    failure_mode=failure_mode,
                )),
                flush=True,
            )


if __name__ == "__main__":
    main()
