"""Per-process message load during a crash experiment: unicast vs gossip.

The paper's Table 2 reports per-process network utilization for a
1000-process crash experiment (Rapid's headline there: mean AND p99 stay
low, unlike ZooKeeper's coordinator-skewed p99). This experiment reproduces
the shape of that measurement on the in-process virtual-time cluster: run an
N-node cluster, crash a few members, converge, and report the distribution
of protocol messages RECEIVED per process (the service's per-type counters)
under each dissemination strategy.

What it shows, concretely: with unicast-to-all every node receives each
broadcast exactly once (the origin pays the whole O(N) send burst); with
gossip every node receives ~fanout x relay_budget copies (the epidemic
redundancy factor -- measured ~8.6x at N=32, fanout=4, budget=2) while any
process's sends per broadcast are bounded by fanout+1 initial sends at the
origin plus relay_budget x fanout relays (13 at the defaults) -- constant
in N, where unicast's origin burst grows linearly.
The per-type totals pin that the PROTOCOL work (alert batches delivered,
votes tallied) is identical under both strategies -- only the
dissemination fabric differs. Run:

    python experiments/message_load.py            (defaults: N=32, crash 2)
    python experiments/message_load.py --n 50 --crash 3

Prints one JSON line per strategy:
  {"strategy", "n", "crashed", "mean_msgs", "p50", "p99", "max",
   "per_type_totals"}
"""

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tests")
)


def run_strategy(strategy: str, n: int, crash: int, seed: int) -> dict:
    from harness import ClusterHarness

    h = ClusterHarness(seed=seed)
    if strategy.startswith("gossip"):
        from rapid_tpu.messaging.gossip import GossipBroadcaster

        mode = "pushpull" if strategy == "gossip-pushpull" else "eager"
        h.broadcaster_factory = lambda client, rng: GossipBroadcaster(
            client, client.address, fanout=4, rng=rng, mode=mode
        )
    try:
        return _measure(h, strategy, n, crash)
    finally:
        h.shutdown()


def _measure(h, strategy: str, n: int, crash: int) -> dict:
    h.create_cluster(n, parallel=False)
    h.wait_and_verify_agreement(n)
    # zero the counters after bootstrap so the measurement is the crash
    # experiment itself, like the paper's steady-state window
    for inst in h.instances.values():
        inst._membership_service.metrics.reset()  # noqa: SLF001
    victims = [h.addr(i) for i in range(2, 2 + crash)]
    h.fail_nodes(victims)
    h.wait_and_verify_agreement(n - crash)

    per_process = []
    per_process_control = []  # payload-free IHAVE/PULL frames (pushpull)
    per_type: dict = {}
    for inst in h.instances.values():
        snap = inst._membership_service.metrics.snapshot()  # noqa: SLF001
        total = sum(
            v for k, v in snap.items()
            if k.startswith("messages.") and not k.endswith(".control")
        )
        per_process.append(total)
        per_process_control.append(
            sum(v for k, v in snap.items() if k.endswith(".control"))
        )
        for k, v in snap.items():
            if k.startswith("messages."):
                per_type[k[len("messages."):]] = per_type.get(k[len("messages."):], 0) + v
    arr = np.array(per_process)
    ctl = np.array(per_process_control)
    return {
        "strategy": strategy,
        "n": n,
        "crashed": crash,
        "mean_msgs": round(float(arr.mean()), 1),
        "p50": int(np.percentile(arr, 50)),
        "p99": int(np.percentile(arr, 99)),
        "max": int(arr.max()),
        "mean_control": round(float(ctl.mean()), 1),
        "per_type_totals": dict(sorted(per_type.items())),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=32)
    parser.add_argument("--crash", type=int, default=2)
    parser.add_argument("--seed", type=int, default=11)
    args = parser.parse_args()
    for strategy in ("unicast", "gossip", "gossip-pushpull"):
        print(
            json.dumps(run_strategy(strategy, args.n, args.crash, args.seed)),
            flush=True,
        )


if __name__ == "__main__":
    main()
