"""Run every BASELINE.json configuration and report time-to-stable-view.

Prints one JSON line per scenario:
  {"config", "n", "virtual_ms", "wall_s", "cut_ok", ...}

- virtual_ms: protocol time a real cluster would need (FD rounds + batching).
- wall_s: simulation wall time on this host/chip.
- cut_ok: the decided cut equals the injected fault set (cut-set parity).

Scenario 1 is the cross-plane parity config: the *protocol plane* (full
object-model cluster with real message passing on the deterministic
virtual-time scheduler) and the *simulation plane* run the same 10-node
membership with the same crash; their cuts, final memberships, and
configuration behavior must agree.
"""

import json
import sys
import time

import numpy as np


def recomputed_config_id(sim) -> int:
    """The configuration id recomputed FROM SCRATCH (fresh element hashes +
    vectorized fold), independent of the driver's per-configuration memo and
    speculative-fold fast paths -- a scenario-level cross-check that the
    incremental identity the protocol stamped on every message equals the
    ground-truth fold over the final membership."""
    from rapid_tpu.sim.topology import configuration_id_vectorized, ring_order

    ids = sim.sorted_identifiers()
    order0 = ring_order(sim.cluster, sim.active, 0)
    vc = sim.cluster
    return configuration_id_vectorized(
        ids[:, 0], ids[:, 1],
        vc.hostnames[order0], vc.host_lengths[order0], vc.ports[order0],
    )


def scenario_10_node_cross_plane():
    """10-node ring, 1 crash-stop: protocol plane vs simulation plane."""
    
    from rapid_tpu import Endpoint
    from rapid_tpu.membership import MembershipView
    from rapid_tpu.sim.driver import Simulator
    from rapid_tpu.types import NodeId
    sys.path.insert(0, "tests")
    from harness import ClusterHarness

    t0 = time.perf_counter()
    # protocol plane
    h = ClusterHarness(seed=1)
    h.create_cluster(10, parallel=False)
    h.wait_and_verify_agreement(10)
    victim = h.addr(9)
    start_virtual = h.scheduler.now_ms()
    h.fail_nodes([victim])
    h.wait_and_verify_agreement(9)
    protocol_virtual_ms = h.scheduler.now_ms() - start_virtual
    survivors_protocol = set(h.instances[h.addr(0)].get_memberlist())
    h.shutdown()

    # simulation plane: same shape of fault
    sim = Simulator(10, seed=1)
    sim.crash(np.array([9]))
    rec = sim.run_until_decision(max_rounds=40)
    cut_ok = rec is not None and list(rec.cut) == [9]

    # cross-plane configuration-id parity on identical identities
    vc = sim.cluster
    view = MembershipView(10)
    for i in range(10):
        host = bytes(vc.hostnames[i, : vc.host_lengths[i]])
        view.ring_add(Endpoint(host, int(vc.ports[i])),
                      NodeId(int(vc.id_high[i]), int(vc.id_low[i])))
    view.ring_delete(Endpoint(
        bytes(vc.hostnames[9, : vc.host_lengths[9]]), int(vc.ports[9])))
    config_parity = view.get_current_configuration_id() == rec.configuration_id

    return {
        "config": "10-node ring, 1 crash-stop (cross-plane parity)",
        "n": 10,
        "virtual_ms": rec.virtual_time_ms,
        "protocol_plane_virtual_ms": protocol_virtual_ms,
        "wall_s": round(time.perf_counter() - t0, 3),
        "cut_ok": bool(cut_ok and len(survivors_protocol) == 9),
        "config_id_parity": bool(config_parity),
    }


def scenario_crash(n, n_fail, seed, label):
    from rapid_tpu.sim.driver import Simulator

    rng = np.random.default_rng(seed)
    sim = Simulator(n, seed=seed)
    victims = rng.choice(n, size=n_fail, replace=False)
    sim.crash(victims)
    t0 = time.perf_counter()
    rec = sim.run_until_decision(max_rounds=32, batch=16)
    wall = time.perf_counter() - t0
    return {
        "config": label,
        "n": n,
        "virtual_ms": rec.virtual_time_ms if rec else None,
        "wall_s": round(wall, 3),
        "cut_ok": bool(rec is not None and set(rec.cut) == set(victims)),
        "config_id_ok": bool(
            rec is not None
            and rec.configuration_id == recomputed_config_id(sim)
        ),
    }


def scenario_one_way_loss(n, n_fail, seed):
    from rapid_tpu.sim.driver import Simulator

    rng = np.random.default_rng(seed)
    sim = Simulator(n, seed=seed)
    victims = rng.choice(n, size=n_fail, replace=False)
    sim.one_way_ingress_partition(victims)
    t0 = time.perf_counter()
    rec = sim.run_until_decision(max_rounds=32, batch=16)
    wall = time.perf_counter() - t0
    return {
        "config": f"{n//1000}k nodes, asymmetric one-way link loss",
        "n": n,
        "virtual_ms": rec.virtual_time_ms if rec else None,
        "wall_s": round(wall, 3),
        "cut_ok": bool(rec is not None and set(rec.cut) == set(victims)),
        "config_id_ok": bool(
            rec is not None
            and rec.configuration_id == recomputed_config_id(sim)
        ),
    }


def scenario_flip_flop_with_join_wave(n, capacity, seed):
    from rapid_tpu.sim.driver import Simulator

    rng = np.random.default_rng(seed)
    sim = Simulator(n, capacity=capacity, seed=seed)
    victims = rng.choice(n, size=n // 100, replace=False)
    joiners = np.arange(n, capacity)
    sim.request_joins(joiners)
    t0 = time.perf_counter()
    flip = True
    decided = []
    for _ in range(12):
        if flip:
            sim.crash(victims)
        else:
            sim.revive(victims)
        flip = not flip
        rec = sim.run_until_decision(max_rounds=10, batch=10)
        if rec is not None:
            decided.append(rec)
            if sim.membership_size == n - len(victims) + len(joiners):
                break
    wall = time.perf_counter() - t0
    final_ok = (
        sim.membership_size == n - len(victims) + len(joiners)
        and not sim.active[victims].any()
        and sim.active[joiners].all()
    )
    return {
        "config": f"{n//1000}k nodes, flip-flop reachability + concurrent join wave",
        "n": n,
        "virtual_ms": decided[-1].virtual_time_ms if decided else None,
        "wall_s": round(wall, 3),
        "cut_ok": bool(final_ok),
        "view_changes": len(decided),
        "config_id_ok": bool(
            decided
            and decided[-1].configuration_id == recomputed_config_id(sim)
        ),
    }


def scenario_nemesis_protocol(plan_seed=7, n=5):
    """The protocol-plane leg of the nemesis run: the same FaultPlan class
    (one-way partition of one node) armed over an in-process virtual-time
    cluster with real ping-pong failure detectors. Rides the telemetry
    plane: every node's spans/metrics attach to the process-global registry,
    so a --trace-out/--metrics-out export carries this leg's protocol spans
    and the simulator leg's device spans on one timeline."""
    from rapid_tpu.faults import FaultPlan
    from rapid_tpu.observability import global_metrics
    sys.path.insert(0, "tests")
    from harness import ClusterHarness

    t0 = time.perf_counter()
    h = ClusterHarness(seed=plan_seed, use_static_fd=False)
    victim = h.addr(n - 1)
    h.with_faults(FaultPlan(seed=plan_seed).partition_one_way(dst=victim))
    h.nemesis.arm(epoch_ms=1 << 40)  # windows far away during bootstrap
    h.start_seed(0)
    for i in range(1, n):
        h.join(i)
        h.wait_and_verify_agreement(i + 1)
    h.nemesis.arm()  # plan time zero = now: the partition opens
    start_virtual = h.scheduler.now_ms()
    vic = h.instances.pop(victim)
    try:
        h.wait_and_verify_agreement(n - 1)
        virtual_ms = h.scheduler.now_ms() - start_virtual
        survivors = set(h.instances[h.addr(0)].get_memberlist())
    finally:
        vic.shutdown()
        h.shutdown()
    stable_view = global_metrics().histogram(
        "time_to_stable_view_ms", plane="protocol"
    )
    return {
        "config": (
            f"nemesis protocol plane: {n} in-process nodes, windowed "
            f"one-way partition (plan seed {plan_seed})"
        ),
        "n": n,
        "virtual_ms": virtual_ms,
        "wall_s": round(time.perf_counter() - t0, 3),
        "cut_ok": bool(victim not in survivors and len(survivors) == n - 1),
        "stable_view_decisions": (
            stable_view["count"] if stable_view is not None else 0
        ),
    }


def scenario_nemesis_smoke(n=1000, plan_seed=7):
    """One seeded FaultPlan compiled onto the device plane's fault arrays
    (rapid_tpu/faults.py): a 1% wave of one-way partitions whose windows
    open 2 s into the run, driven through every schedule boundary by
    replay_on_simulator. The same FaultPlan class drives the in-process and
    TCP transports (tests/test_faults.py pins the three-plane parity)."""
    from rapid_tpu.faults import FaultPlan, endpoint_slots, replay_on_simulator
    from rapid_tpu.sim.driver import Simulator

    sim = Simulator(n, seed=plan_seed)
    by_slot = {slot: ep for ep, slot in endpoint_slots(sim).items()}
    rng = np.random.default_rng(plan_seed)
    victims = sorted(
        int(v) for v in rng.choice(n, size=max(1, n // 100), replace=False)
    )
    plan = FaultPlan(seed=plan_seed)
    for v in victims:
        plan.partition_one_way(dst=by_slot[v], windows=((2000, None),))
    t0 = time.perf_counter()
    records = replay_on_simulator(sim, plan, duration_ms=60_000)
    wall = time.perf_counter() - t0
    cut = sorted({int(c) for rec in records for c in rec.cut})
    return {
        "config": (
            f"nemesis smoke: {len(victims)} windowed one-way partitions "
            f"(plan seed {plan_seed})"
        ),
        "n": n,
        "virtual_ms": records[-1].virtual_time_ms if records else None,
        "wall_s": round(wall, 3),
        "cut_ok": bool(cut == victims),
        "config_id_ok": bool(
            records
            and records[-1].configuration_id == recomputed_config_id(sim)
        ),
    }


def _flag_value(flag: str) -> str:
    """Value of ``--flag PATH`` in sys.argv, or '' when absent."""
    if flag not in sys.argv:
        return ""
    at = sys.argv.index(flag)
    return sys.argv[at + 1] if len(sys.argv) > at + 1 else ""


def _write_telemetry() -> None:
    """Honor --trace-out / --metrics-out: export the process-global
    telemetry plane (every scenario's protocol nodes + simulators merged).
    The Chrome trace loads in Perfetto / chrome://tracing; the metrics file
    is Prometheus text exposition (see ARCHITECTURE.md, Telemetry plane)."""
    from rapid_tpu.observability import write_chrome_trace, write_prometheus

    trace_out = _flag_value("--trace-out")
    metrics_out = _flag_value("--metrics-out")
    if trace_out:
        write_chrome_trace(trace_out)
        print(json.dumps({"trace_out": trace_out}))
    if metrics_out:
        write_prometheus(metrics_out)
        print(json.dumps({"metrics_out": metrics_out}))


def main() -> None:
    if "--tpu" not in sys.argv:
        # pin the CPU backend via the CONFIG value (an injected accelerator
        # plugin ignores the env var, and a dead remote-TPU tunnel hangs
        # device init); pass --tpu to run on real hardware
        import jax

        jax.config.update("jax_platforms", "cpu")
    if "--fault-plan" in sys.argv:
        # replay one seeded nemesis FaultPlan on the protocol plane AND the
        # device plane, then exit (with telemetry exports if requested):
        #   python scenarios.py --fault-plan [seed] \
        #       [--trace-out trace.json] [--metrics-out metrics.prom]
        arg = _flag_value("--fault-plan")
        plan_seed = int(arg) if arg.lstrip("-").isdigit() else 7
        print(json.dumps(scenario_nemesis_protocol(plan_seed=plan_seed)))
        print(json.dumps(scenario_nemesis_smoke(plan_seed=plan_seed)))
        _write_telemetry()
        return
    results = [
        scenario_10_node_cross_plane(),
        scenario_crash(1000, 1, 100, "1k virtual nodes, single crash-stop fault"),
        scenario_crash(10_000, 100, 200, "10k virtual nodes, 1% correlated crash burst"),
        scenario_one_way_loss(50_000, 500, 300),
        scenario_flip_flop_with_join_wave(100_000, 100_100, 400),
        scenario_nemesis_smoke(),
    ]
    if "--scale-1m" in sys.argv:
        # first-class targets at 10x the north-star scale (VERDICT r4 item
        # 3): every failure class the paper holds stable, at 1M, with cut
        # parity AND the from-scratch configuration-id cross-check
        results.append(
            scenario_crash(
                1_000_000, 10_000, 500,
                "1M virtual nodes, 1% correlated crash burst (10x north star)",
            )
        )
        results.append(scenario_one_way_loss(1_000_000, 10_000, 501))
        results.append(
            scenario_flip_flop_with_join_wave(1_000_000, 1_001_000, 502)
        )
    for result in results:
        print(json.dumps(result))
    _write_telemetry()


if __name__ == "__main__":
    main()
