"""Run every BASELINE.json configuration and report time-to-stable-view.

Prints one JSON line per scenario:
  {"scenario", "config", "n", "virtual_ms", "wall_s", "cut_ok", ...}

- virtual_ms: protocol time a real cluster would need (FD rounds + batching).
- wall_s: simulation wall time on this host/chip.
- cut_ok: the decided cut equals the injected fault set (cut-set parity).

Every scenario is registered by name in REGISTRY with its parameters bound
once (seed, scale, label), so the battery, ``--list`` and ``--scenario NAME``
all read the same table instead of hand-rolling per-entry wiring:

  python scenarios.py                  # the default battery
  python scenarios.py --list           # names + parameters, no jax needed
  python scenarios.py --scenario gray-slow-node [--seed 9]
  python scenarios.py --fault-plan [seed]   # nemesis pair (protocol+device)
  python scenarios.py --scale-1m       # battery + the 1M-node targets

Scenario "cross-plane-10" is the cross-plane parity config: the *protocol
plane* (full object-model cluster with real message passing on the
deterministic virtual-time scheduler) and the *simulation plane* run the
same 10-node membership with the same crash; their cuts, final memberships,
and configuration behavior must agree.

The gray-failure quartet (ISSUE 6) rides the same registry:

- wan-zone-loss: a LatencyTopology (racks/zones/regions) compiled onto the
  device plane's delivery groups + broadcast-delay rounds, then one whole
  zone partitioned; reports per-zone detection->decision latency.
- gray-slow-node: a node that answers EVERY message, just slower than the
  probe deadline -- alive, processing, and evicted with zero collateral.
- gray-flapping: a node oscillating slow/healthy across three windows; the
  adaptive FD (ISSUE 14) must evict inside the first slow window with zero
  collateral evictions and no view flip-flop afterwards.
- clock-skew: one node's entire timer stack runs on a drifted clock while
  the cluster churns through a join + a crash around it.
- rolling-upgrade: a mixed wire-version cluster (half the nodes encode with
  reserved ``__``-prefixed extension keys / thinned optional fields)
  converging through a join + removal wave under probe loss.

The durability plane (PR 16) adds rolling-restart: every node in sequence
crashes abruptly and rejoins with its WAL directory under serving load --
old identities retained, zero lost acked writes, zero spurious evictions.
"""

import json
import pathlib
import sys
import time

import numpy as np


def recomputed_config_id(sim) -> int:
    """The configuration id recomputed FROM SCRATCH (fresh element hashes +
    vectorized fold), independent of the driver's per-configuration memo and
    speculative-fold fast paths -- a scenario-level cross-check that the
    incremental identity the protocol stamped on every message equals the
    ground-truth fold over the final membership."""
    from rapid_tpu.sim.topology import configuration_id_vectorized, ring_order

    ids = sim.sorted_identifiers()
    order0 = ring_order(sim.cluster, sim.active, 0)
    vc = sim.cluster
    return configuration_id_vectorized(
        ids[:, 0], ids[:, 1],
        vc.hostnames[order0], vc.host_lengths[order0], vc.ports[order0],
    )


# ---------------------------------------------------------------------------
# registry: one table binding scenario name -> (function, bound parameters);
# the battery, --list and --scenario all read it (previously each main()
# entry hand-rolled its own seed/label wiring)
# ---------------------------------------------------------------------------

REGISTRY: "dict[str, tuple]" = {}


def register(name: str, fn, **params) -> None:
    assert name not in REGISTRY, f"duplicate scenario name {name!r}"
    REGISTRY[name] = (fn, params)


def run_scenario(
    name: str, seed: "int | None" = None, **overrides
) -> dict:
    """Run one registered scenario; ``seed`` overrides the bound seed, and
    further keyword overrides replace bound params (the --cells knob)."""
    fn, params = REGISTRY[name]
    if seed is not None:
        params = {**params, "seed": seed}
    if overrides:
        params = {**params, **overrides}
    result = fn(**params)
    result["scenario"] = name
    return result


def _bootstrap(h, n: int) -> None:
    """Sequential bootstrap to n nodes with per-step agreement, the armed
    nemesis dormant (windows shifted to a far-future epoch) so fault windows
    cannot starve join alerts; callers re-arm at plan-time zero afterwards."""
    h.nemesis.arm(epoch_ms=1 << 40)
    h.start_seed(0)
    for i in range(1, n):
        h.join(i)
        h.wait_and_verify_agreement(i + 1)


def scenario_10_node_cross_plane(seed=1):
    """10-node ring, 1 crash-stop: protocol plane vs simulation plane."""

    from rapid_tpu import Endpoint
    from rapid_tpu.membership import MembershipView
    from rapid_tpu.sim.driver import Simulator
    from rapid_tpu.types import NodeId
    sys.path.insert(0, "tests")
    from harness import ClusterHarness

    t0 = time.perf_counter()
    # protocol plane
    h = ClusterHarness(seed=seed)
    h.create_cluster(10, parallel=False)
    h.wait_and_verify_agreement(10)
    victim = h.addr(9)
    start_virtual = h.scheduler.now_ms()
    h.fail_nodes([victim])
    h.wait_and_verify_agreement(9)
    protocol_virtual_ms = h.scheduler.now_ms() - start_virtual
    survivors_protocol = set(h.instances[h.addr(0)].get_memberlist())
    h.shutdown()

    # simulation plane: same shape of fault
    sim = Simulator(10, seed=seed)
    sim.crash(np.array([9]))
    rec = sim.run_until_decision(max_rounds=40)
    cut_ok = rec is not None and list(rec.cut) == [9]

    # cross-plane configuration-id parity on identical identities
    vc = sim.cluster
    view = MembershipView(10)
    for i in range(10):
        host = bytes(vc.hostnames[i, : vc.host_lengths[i]])
        view.ring_add(Endpoint(host, int(vc.ports[i])),
                      NodeId(int(vc.id_high[i]), int(vc.id_low[i])))
    view.ring_delete(Endpoint(
        bytes(vc.hostnames[9, : vc.host_lengths[9]]), int(vc.ports[9])))
    config_parity = view.get_current_configuration_id() == rec.configuration_id

    return {
        "config": "10-node ring, 1 crash-stop (cross-plane parity)",
        "n": 10,
        "virtual_ms": rec.virtual_time_ms,
        "protocol_plane_virtual_ms": protocol_virtual_ms,
        "wall_s": round(time.perf_counter() - t0, 3),
        "cut_ok": bool(cut_ok and len(survivors_protocol) == 9),
        "config_id_parity": bool(config_parity),
    }


def _hierarchy_digest(sim) -> dict:
    """Result fields for a sim with the hierarchy mirror attached: the
    composed rows, parent-round bill, and the incremental-vs-scratch
    fingerprint cross-check (the sim analogue of every member agreeing)."""
    rows = sim.hierarchy_rows()
    incremental = sim.global_fingerprint()
    for state in list(rows):
        sim._hierarchy_recompute_cell(state.cell)
    return {
        "cells": {int(r.cell): int(r.size) for r in rows},
        "parent_rounds": sim.parent_rounds,
        "global_fingerprint": incremental,
        "fingerprint_ok": bool(incremental == sim.global_fingerprint()),
    }


def scenario_crash(n, n_fail, seed, label, cells=0):
    from rapid_tpu.sim.driver import Simulator

    rng = np.random.default_rng(seed)
    sim = Simulator(n, seed=seed)
    if cells:
        sim.enable_hierarchy(cells=cells)
    victims = rng.choice(n, size=n_fail, replace=False)
    sim.crash(victims)
    t0 = time.perf_counter()
    rec = sim.run_until_decision(max_rounds=32, batch=16)
    wall = time.perf_counter() - t0
    result = {
        "config": label,
        "n": n,
        "virtual_ms": rec.virtual_time_ms if rec else None,
        "wall_s": round(wall, 3),
        "cut_ok": bool(rec is not None and set(rec.cut) == set(victims)),
        "config_id_ok": bool(
            rec is not None
            and rec.configuration_id == recomputed_config_id(sim)
        ),
    }
    if cells:
        result["hierarchy"] = _hierarchy_digest(sim)
    return result


def scenario_one_way_loss(n, n_fail, seed, cells=0):
    from rapid_tpu.sim.driver import Simulator

    rng = np.random.default_rng(seed)
    sim = Simulator(n, seed=seed)
    if cells:
        sim.enable_hierarchy(cells=cells)
    victims = rng.choice(n, size=n_fail, replace=False)
    sim.one_way_ingress_partition(victims)
    t0 = time.perf_counter()
    rec = sim.run_until_decision(max_rounds=32, batch=16)
    wall = time.perf_counter() - t0
    result = {
        "config": f"{n//1000}k nodes, asymmetric one-way link loss",
        "n": n,
        "virtual_ms": rec.virtual_time_ms if rec else None,
        "wall_s": round(wall, 3),
        "cut_ok": bool(rec is not None and set(rec.cut) == set(victims)),
        "config_id_ok": bool(
            rec is not None
            and rec.configuration_id == recomputed_config_id(sim)
        ),
    }
    if cells:
        result["hierarchy"] = _hierarchy_digest(sim)
    return result


def scenario_flip_flop_with_join_wave(n, capacity, seed, cells=0):
    from rapid_tpu.sim.driver import Simulator

    rng = np.random.default_rng(seed)
    sim = Simulator(n, capacity=capacity, seed=seed)
    if cells:
        sim.enable_hierarchy(cells=cells)
    victims = rng.choice(n, size=n // 100, replace=False)
    joiners = np.arange(n, capacity)
    sim.request_joins(joiners)
    t0 = time.perf_counter()
    flip = True
    decided = []
    for _ in range(12):
        if flip:
            sim.crash(victims)
        else:
            sim.revive(victims)
        flip = not flip
        rec = sim.run_until_decision(max_rounds=10, batch=10)
        if rec is not None:
            decided.append(rec)
            if sim.membership_size == n - len(victims) + len(joiners):
                break
    wall = time.perf_counter() - t0
    final_ok = (
        sim.membership_size == n - len(victims) + len(joiners)
        and not sim.active[victims].any()
        and sim.active[joiners].all()
    )
    result = {
        "config": f"{n//1000}k nodes, flip-flop reachability + concurrent join wave",
        "n": n,
        "virtual_ms": decided[-1].virtual_time_ms if decided else None,
        "wall_s": round(wall, 3),
        "cut_ok": bool(final_ok),
        "view_changes": len(decided),
        "config_id_ok": bool(
            decided
            and decided[-1].configuration_id == recomputed_config_id(sim)
        ),
    }
    if cells:
        result["hierarchy"] = _hierarchy_digest(sim)
    return result


def scenario_nemesis_protocol(seed=7, n=5):
    """The protocol-plane leg of the nemesis run: the same FaultPlan class
    (one-way partition of one node) armed over an in-process virtual-time
    cluster with real ping-pong failure detectors. Rides the telemetry
    plane: every node's spans/metrics attach to the process-global registry,
    so a --trace-out/--metrics-out export carries this leg's protocol spans
    and the simulator leg's device spans on one timeline."""
    from rapid_tpu.faults import FaultPlan
    from rapid_tpu.observability import global_metrics
    sys.path.insert(0, "tests")
    from harness import ClusterHarness

    t0 = time.perf_counter()
    h = ClusterHarness(seed=seed, use_static_fd=False)
    victim = h.addr(n - 1)
    h.with_faults(FaultPlan(seed=seed).partition_one_way(dst=victim))
    _bootstrap(h, n)
    h.nemesis.arm()  # plan time zero = now: the partition opens
    start_virtual = h.scheduler.now_ms()
    vic = h.instances.pop(victim)
    try:
        h.wait_and_verify_agreement(n - 1)
        virtual_ms = h.scheduler.now_ms() - start_virtual
        survivors = set(h.instances[h.addr(0)].get_memberlist())
    finally:
        vic.shutdown()
        h.shutdown()
    stable_view = global_metrics().histogram(
        "time_to_stable_view_ms", plane="protocol"
    )
    return {
        "config": (
            f"nemesis protocol plane: {n} in-process nodes, windowed "
            f"one-way partition (plan seed {seed})"
        ),
        "n": n,
        "virtual_ms": virtual_ms,
        "wall_s": round(time.perf_counter() - t0, 3),
        "cut_ok": bool(victim not in survivors and len(survivors) == n - 1),
        "stable_view_decisions": (
            stable_view["count"] if stable_view is not None else 0
        ),
    }


def scenario_nemesis_smoke(n=1000, seed=7):
    """One seeded FaultPlan compiled onto the device plane's fault arrays
    (rapid_tpu/faults.py): a 1% wave of one-way partitions whose windows
    open 2 s into the run, driven through every schedule boundary by
    replay_on_simulator. The same FaultPlan class drives the in-process and
    TCP transports (tests/test_faults.py pins the three-plane parity)."""
    from rapid_tpu.faults import FaultPlan, endpoint_slots, replay_on_simulator
    from rapid_tpu.sim.driver import Simulator

    sim = Simulator(n, seed=seed)
    by_slot = {slot: ep for ep, slot in endpoint_slots(sim).items()}
    rng = np.random.default_rng(seed)
    victims = sorted(
        int(v) for v in rng.choice(n, size=max(1, n // 100), replace=False)
    )
    plan = FaultPlan(seed=seed)
    for v in victims:
        plan.partition_one_way(dst=by_slot[v], windows=((2000, None),))
    t0 = time.perf_counter()
    records = replay_on_simulator(sim, plan, duration_ms=60_000)
    wall = time.perf_counter() - t0
    cut = sorted({int(c) for rec in records for c in rec.cut})
    return {
        "config": (
            f"nemesis smoke: {len(victims)} windowed one-way partitions "
            f"(plan seed {seed})"
        ),
        "n": n,
        "virtual_ms": records[-1].virtual_time_ms if records else None,
        "wall_s": round(wall, 3),
        "cut_ok": bool(cut == victims),
        "config_id_ok": bool(
            records
            and records[-1].configuration_id == recomputed_config_id(sim)
        ),
    }


# ---------------------------------------------------------------------------
# ISSUE 6: gray failures + WAN topology
# ---------------------------------------------------------------------------


def scenario_wan_zone_loss(seed=11, n=1024):
    """WAN device plane: a 16-rack / 8-zone / 2-region LatencyTopology with a
    1000 ms inter-region RTT compiled onto delivery groups + broadcast-delay
    rounds, then every node of one zone one-way partitioned 2 s in. Reports
    per-zone detection->decision latency (also observed into the
    nemesis_zone_detection_ms histogram, so --metrics-out / --trace-out
    exports carry it)."""
    from rapid_tpu.faults import FaultPlan, endpoint_slots, replay_on_simulator
    from rapid_tpu.observability import global_metrics
    from rapid_tpu.sim.driver import Simulator
    from rapid_tpu.sim.engine import SimConfig
    from rapid_tpu.sim.topology import LatencyTopology

    topo = LatencyTopology(racks=16, zones=8, regions=2,
                           rack_rtt_ms=0, zone_rtt_ms=2, region_rtt_ms=4,
                           inter_region_rtt_ms=1000)
    config = SimConfig(capacity=n, groups=8, max_delivery_delay=2,
                       rounds_per_interval=4)
    sim = Simulator(n, config=config, seed=seed)
    by_slot = {slot: ep for ep, slot in endpoint_slots(sim).items()}
    lost_zone = 7
    victims = [i for i in range(n) if topo.zone_of(i) == lost_zone]
    plan = FaultPlan(seed=seed).with_topology(topo)
    for v in victims:
        plan.partition_one_way(dst=by_slot[v], windows=((2000, None),))
    t0 = time.perf_counter()
    records = replay_on_simulator(sim, plan, duration_ms=120_000)
    wall = time.perf_counter() - t0
    cut = sorted({int(c) for rec in records for c in rec.cut})
    # detection -> decision latency per zone touched by a decision, measured
    # from the partition window opening (virtual_time_ms is absolute and the
    # simulator starts at 0, so the offset is exactly the window start)
    per_zone = {}
    for rec in records:
        for z in sorted({topo.zone_of(int(c)) for c in rec.cut}):
            if z not in per_zone:
                per_zone[z] = rec.virtual_time_ms - 2000
                global_metrics().observe(
                    "nemesis_zone_detection_ms", per_zone[z], zone=str(z)
                )
    return {
        "config": (
            f"WAN zone loss: {n} slots over 8 zones x 2 regions, 1000 ms "
            f"inter-region RTT, zone {lost_zone} partitioned (seed {seed})"
        ),
        "n": n,
        "virtual_ms": records[-1].virtual_time_ms if records else None,
        "wall_s": round(wall, 3),
        "cut_ok": bool(cut == victims),
        "config_id_ok": bool(
            records
            and records[-1].configuration_id == recomputed_config_id(sim)
        ),
        "zone_detection_ms": per_zone,
    }


def scenario_hierarchy_zone_churn(seed=19, zones=8, per_zone=256):
    """Hierarchy plane: ``zones`` topology cells of ``per_zone`` members
    each, ordinary churn in flight (a scatter of crashes across cells),
    then one whole cell -- its deterministic leader included -- killed.

    Oracle: the surviving cells' composed global view agrees (the
    incremental composition matches a from-scratch recompute and the dead
    cell's row is gone), the lost cell is evicted in O(1) parent rounds
    (bounded by the view changes, never by member count), and there are
    zero collateral evictions (the union of cuts is exactly the union of
    victims)."""
    from rapid_tpu.hierarchy.parent import cell_leaders
    from rapid_tpu.sim.driver import Simulator
    from rapid_tpu.sim.engine import SimConfig
    from rapid_tpu.sim.topology import LatencyTopology
    from rapid_tpu.types import Endpoint

    n = zones * per_zone
    topo = LatencyTopology(racks=zones * 2, zones=zones,
                           rack_rtt_ms=0, zone_rtt_ms=2, region_rtt_ms=4,
                           inter_region_rtt_ms=8)
    rng = np.random.default_rng(seed)
    sim = Simulator(n, config=SimConfig(capacity=n, groups=8), seed=seed)
    sim.enable_hierarchy(topology=topo, parent_round_ms=4)
    lost_zone = int(rng.integers(zones))
    zone_victims = [i for i in range(n) if topo.zone_of(i) == lost_zone]
    # the zone kill provably includes the cell's deterministic leader
    members = [
        Endpoint(hostname=h, port=p)
        for h, p in (sim.endpoint_of(s) for s in zone_victims)
    ]
    leader = str(cell_leaders(members, 1)[0])
    assert leader in {str(m) for m in members}
    # mid-churn: a scatter of ordinary crashes lands first
    others = [i for i in range(n) if topo.zone_of(i) != lost_zone]
    scatter = [int(i) for i in rng.choice(others, size=8, replace=False)]
    t0 = time.perf_counter()
    sim.crash(np.array(scatter))
    records = [sim.run_until_decision(max_rounds=32, batch=16)]
    sim.crash(np.array(zone_victims))
    records.append(sim.run_until_decision(max_rounds=32, batch=16))
    wall = time.perf_counter() - t0
    records = [r for r in records if r is not None]
    cut = sorted({int(c) for rec in records for c in rec.cut})
    digest = _hierarchy_digest(sim)
    surviving = set(range(zones)) - {lost_zone}
    return {
        "config": (
            f"hierarchy zone churn: {zones} cells x {per_zone} members, "
            f"scatter crashes then whole cell {lost_zone} killed, leader "
            f"{leader} included (seed {seed})"
        ),
        "n": n,
        "virtual_ms": records[-1].virtual_time_ms if records else None,
        "wall_s": round(wall, 3),
        # zero collateral evictions: exactly the victims were cut
        "cut_ok": bool(cut == sorted(scatter + zone_victims)),
        "config_id_ok": bool(
            records
            and records[-1].configuration_id == recomputed_config_id(sim)
        ),
        "hierarchy": digest,
        "cell_evicted_ok": bool(
            set(digest["cells"]) == surviving
            and digest["fingerprint_ok"]
        ),
        # O(1) parent rounds: one per composition move, bounded by the
        # two churn edges -- independent of the 256-member cell size
        "parent_rounds_ok": bool(
            0 < digest["parent_rounds"] <= len(records) + 1
        ),
    }


def scenario_gray_slow_node(seed=7, n=5, response_delay_ms=5000):
    """Gray failure: node n-1 answers EVERY message, just response_delay_ms
    late -- past the probe deadline, so observers see timeouts while the
    victim stays alive, keeps processing, and never crashes. The survivors
    must evict exactly the slow node (zero collateral evictions), and the
    same plan replayed on the device plane with the protocol plane's seated
    identities must produce the same cut and configuration id."""
    from rapid_tpu.faults import FaultPlan, replay_on_simulator
    from rapid_tpu.observability import global_metrics
    from rapid_tpu.sim.driver import Simulator
    sys.path.insert(0, "tests")
    from harness import ClusterHarness

    t0 = time.perf_counter()
    h = ClusterHarness(seed=seed, use_static_fd=False)
    victim = h.addr(n - 1)

    def plan():
        return FaultPlan(seed=seed).slow_node(victim, response_delay_ms)

    h.with_faults(plan())
    _bootstrap(h, n)
    full_cfg = (
        h.instances[h.addr(0)]._membership_service._view.get_configuration()
    )
    hist = global_metrics().histogram("fd.rtt_ms")
    rtt_before = hist["count"] if hist is not None else 0
    h.nemesis.arm()  # the victim turns gray now
    start_virtual = h.scheduler.now_ms()
    vic = h.instances.pop(victim)  # keeps RUNNING: slow, not dead
    try:
        h.wait_and_verify_agreement(n - 1)
        virtual_ms = h.scheduler.now_ms() - start_virtual
        survivor = h.instances[h.addr(0)]
        survivors = set(survivor.get_memberlist())
        ip_config = survivor.get_current_configuration_id()
        victim_alive = vic.get_membership_size() >= 1
    finally:
        vic.shutdown()
        h.shutdown()
    expected = {h.addr(i) for i in range(n - 1)}
    hist = global_metrics().histogram("fd.rtt_ms")
    rtt_samples = (hist["count"] if hist is not None else 0) - rtt_before

    # device leg: seat the protocol plane's identities; a slower-than-round
    # response compiles to the partition-equivalent cut
    identities = [
        (ep.hostname, ep.port, nid.high, nid.low)
        for ep, nid in zip(
            (h.addr(i) for i in range(n)), full_cfg.node_ids
        )
    ]
    sim = Simulator(n, seed=seed, identities=identities)
    records = replay_on_simulator(sim, plan(), duration_ms=40_000)
    device_ok = (
        len(records) == 1
        and [int(c) for c in records[0].cut] == [n - 1]
        and records[0].configuration_id == ip_config
    )
    return {
        "config": (
            f"gray slow node: {n} nodes, victim answers "
            f"{response_delay_ms} ms late (seed {seed})"
        ),
        "n": n,
        "virtual_ms": virtual_ms,
        "wall_s": round(time.perf_counter() - t0, 3),
        "cut_ok": bool(survivors == expected and victim_alive),
        "config_id_parity": bool(device_ok),
        "fd_rtt_samples": int(rtt_samples),
    }


def scenario_gray_flapping(seed=17, n=5, response_delay_ms=5000):
    """Gray flapping: node n-1 oscillates between slow (answers every message
    ``response_delay_ms`` late) and fully healthy across three 20 s slow
    windows separated by 20 s healthy gaps. The adaptive failure detector
    (Settings.adaptive_fd) must convert the miss streak into an eviction
    within the FIRST slow window's budget -- before a healthy gap can reset
    a windowed score -- with zero collateral evictions, and the view must
    not flip-flop when the later windows open and close around the already
    evicted node."""
    from rapid_tpu.faults import FaultPlan
    from rapid_tpu.observability import global_metrics
    from rapid_tpu.settings import AdaptiveFdSettings, Settings
    sys.path.insert(0, "tests")
    from harness import ClusterHarness

    t0 = time.perf_counter()
    slow_windows = ((0, 20_000), (40_000, 60_000), (80_000, 100_000))
    settings = Settings(adaptive_fd=AdaptiveFdSettings(enabled=True))
    h = ClusterHarness(seed=seed, use_static_fd=False, settings=settings)
    victim = h.addr(n - 1)
    h.with_faults(
        FaultPlan(seed=seed).slow_node(
            victim, response_delay_ms, windows=slow_windows
        )
    )
    _bootstrap(h, n)
    # soak healthy: gray scoring only activates on warmed-up edges
    # (adaptive_fd.warmup_probes successful samples), and a real gray fault
    # hits a long-running cluster, not one mid-bootstrap
    h.scheduler.run_until(lambda: False, timeout_ms=8_000)

    def gray_alert_total() -> int:
        return sum(
            value
            for kind, name, _labels, value in global_metrics().collect()
            if kind == "counter" and name == "fd.gray_alerts"
        )

    gray_before = gray_alert_total()
    h.nemesis.arm()  # window 1 opens: the victim turns gray now
    start_virtual = h.scheduler.now_ms()
    vic = h.instances.pop(victim)  # keeps RUNNING: flapping, not dead
    try:
        h.wait_and_verify_agreement(n - 1)
        detect_ms = h.scheduler.now_ms() - start_virtual
        survivor = h.instances[h.addr(0)]
        survivors = set(survivor.get_memberlist())
        config_after_cut = survivor.get_current_configuration_id()
        # ride out the healthy gap + windows 2 and 3: the evicted node
        # flapping back to healthy (and slow again) must not re-enter the
        # view or cut anyone else -- no flip-flop
        h.scheduler.run_until(
            lambda: False,
            timeout_ms=slow_windows[-1][1] + 20_000 - detect_ms,
        )
        virtual_ms = h.scheduler.now_ms() - start_virtual
        stable = (
            set(survivor.get_memberlist()) == survivors
            and survivor.get_current_configuration_id() == config_after_cut
        )
        victim_alive = vic.get_membership_size() >= 1
    finally:
        vic.shutdown()
        h.shutdown()
    expected = {h.addr(i) for i in range(n - 1)}
    gray_alerts = gray_alert_total() - gray_before
    # budget: the cut must land inside slow window 1 (20 s); a detector that
    # needs the flapping node to stay gray across windows would miss this
    window_budget_ms = slow_windows[0][1] - slow_windows[0][0]
    return {
        "config": (
            f"gray flapping: {n} nodes, victim {response_delay_ms} ms late "
            f"across {len(slow_windows)} windows (seed {seed})"
        ),
        "n": n,
        "virtual_ms": virtual_ms,
        "detect_ms": detect_ms,
        "wall_s": round(time.perf_counter() - t0, 3),
        "cut_ok": bool(
            survivors == expected
            and victim_alive
            and detect_ms <= window_budget_ms
            and stable
            and gray_alerts > 0  # the adaptive path drove it, not fallback
        ),
        "gray_alerts": int(gray_alerts),
    }


def scenario_clock_skew(seed=13, n=5, offset_ms=350, rate=1.25):
    """One node's ENTIRE timer stack -- FD probe intervals, batching windows,
    retry backoff, message deadlines -- runs on a clock drifting at ``rate``x
    true time plus ``offset_ms``, while every peer keeps true time. The
    cluster must still bootstrap, admit a joiner and evict a crashed node
    with zero collateral eviction of the skewed node."""
    from rapid_tpu.faults import FaultPlan
    sys.path.insert(0, "tests")
    from harness import ClusterHarness

    t0 = time.perf_counter()
    h = ClusterHarness(seed=seed, use_static_fd=False)
    skewed = h.addr(1)
    h.with_faults(
        FaultPlan(seed=seed).clock_skew(skewed, offset_ms=offset_ms, rate=rate)
    )
    _bootstrap(h, n)
    h.nemesis.arm()
    start_virtual = h.scheduler.now_ms()
    h.join(n)  # a join wave under skew ...
    h.wait_and_verify_agreement(n + 1)
    crashed = h.addr(n - 1)
    h.fail_nodes([crashed])  # ... then a crash-stop beside the skewed node
    try:
        h.wait_and_verify_agreement(n)
        virtual_ms = h.scheduler.now_ms() - start_virtual
        members = set(h.instances[h.addr(0)].get_memberlist())
        drift_ms = (
            h.nemesis.scheduler_for(skewed).now_ms() - h.scheduler.now_ms()
        )
    finally:
        h.shutdown()
    ok = skewed in members and crashed not in members and len(members) == n
    return {
        "config": (
            f"clock skew: {n} nodes + joiner, node 1 at {rate}x "
            f"+{offset_ms} ms, one crash-stop (seed {seed})"
        ),
        "n": n,
        "virtual_ms": virtual_ms,
        "wall_s": round(time.perf_counter() - t0, 3),
        "cut_ok": bool(ok),
        "skew_drift_ms": int(drift_ms),
    }


def scenario_rolling_upgrade(seed=21, n=6, version=2):
    """Rolling upgrade: the even-indexed half of the cluster (and the
    joiner) encodes every egress message at wire version ``version`` --
    reserved ``__``-prefixed extension keys a v1 peer must ignore, optional
    defaulted fields thinned -- while the rest speak v1, with a sustained 5%
    probe-lossy link riding along. The mixed-version cluster bootstraps,
    admits the upgraded joiner and evicts a v1 node, all on bytes a
    same-version cluster never exercises (PR 3's __tc stripping generalized
    into versioned-wire replay). Windowed FDs shed the probe loss."""
    from rapid_tpu import Settings
    from rapid_tpu.faults import FaultPlan
    from rapid_tpu.types import ProbeMessage
    sys.path.insert(0, "tests")
    from harness import ClusterHarness

    t0 = time.perf_counter()
    settings = Settings(fd_policy="windowed")
    h = ClusterHarness(seed=seed, use_static_fd=False, settings=settings)
    plan = FaultPlan(seed=seed).lossy_link(0.05, msg_types=(ProbeMessage,))
    for i in list(range(0, n, 2)) + [n]:
        plan.wire_version(h.addr(i), version)
    h.with_faults(plan)
    # armed from epoch zero: the whole bootstrap runs on mixed wire versions
    h.nemesis.arm()
    h.start_seed(0)
    for i in range(1, n):
        h.join(i)
        h.wait_and_verify_agreement(i + 1)
    start_virtual = h.scheduler.now_ms()
    h.join(n)  # the upgraded joiner arrives on v2 bytes
    h.wait_and_verify_agreement(n + 1)
    h.fail_nodes([h.addr(n - 1)])  # a v1 node leaves mid-upgrade
    try:
        h.wait_and_verify_agreement(n)
        virtual_ms = h.scheduler.now_ms() - start_virtual
        members = set(h.instances[h.addr(0)].get_memberlist())
        versioned = h.nemesis.metrics.get("nemesis_wire_versioned")
    finally:
        h.shutdown()
    expected = {h.addr(i) for i in range(n + 1)} - {h.addr(n - 1)}
    return {
        "config": (
            f"rolling upgrade: {n} nodes half at wire v{version} + v{version} "
            f"joiner, 5% probe loss, one v1 removal (seed {seed})"
        ),
        "n": n,
        "virtual_ms": virtual_ms,
        "wall_s": round(time.perf_counter() - t0, 3),
        "cut_ok": bool(members == expected),
        "wire_versioned_msgs": int(versioned),
    }


def scenario_serving_sawtooth(seed=31, n=16, wave=4, waves=3, ops=80):
    """Elastic autoscaling sawtooth under sustained serving load: each wave
    joins ``wave`` fresh nodes, serves closed-loop Get/Put traffic, then
    gracefully drains the same nodes back out -- membership sawtooths
    n -> n+wave -> n while the serving plane's KV data rides every
    placement diff through verified handoff sessions. The invariant the
    scenario pins: ZERO acknowledged writes lost across the whole sawtooth
    (after every view settles, each oracle-recorded ack reads back at >=
    its acked version). Fully deterministic per seed: latencies bill on
    virtual time and the workload is seeded."""
    from rapid_tpu.sim.driver import Simulator

    rng = np.random.default_rng(seed)
    capacity = n + waves * wave
    sim = Simulator(n, capacity=capacity, seed=seed)
    sim.enable_placement(partitions=128, replicas=3)
    sim.enable_handoff(chunk_ms=1)
    sim.enable_serving()
    keys = [b"saw-%03d" % i for i in range(48)]

    def drive(count: int) -> int:
        served = 0
        for _ in range(count):
            key = keys[int(rng.integers(len(keys)))]
            if rng.random() < 0.25:
                ack = sim.serving_put(key, b"w-%d" % sim.virtual_ms)
            else:
                ack = sim.serving_get(key)
            if ack.status != ack.STATUS_RETRY:
                served += 1
        return served

    def settle(expected_size: int) -> int:
        changes = 0
        for _ in range(6):
            if sim.membership_size == expected_size:
                break
            rec = sim.run_until_decision(max_rounds=40, batch=10)
            if rec is not None:
                changes += 1
        assert sim.membership_size == expected_size, (
            f"sawtooth stuck at {sim.membership_size}, want {expected_size}"
        )
        return changes

    def lost_acked() -> int:
        lost = 0
        for key, (version, _) in sim.serving_acked.items():
            back = sim.serving_get(key)
            if back.status != back.STATUS_OK or back.version < version:
                lost += 1
        return lost

    t0 = time.perf_counter()
    for i, key in enumerate(keys):
        ack = sim.serving_put(key, b"seed-%d" % i)
        assert ack.status == ack.STATUS_OK
    total_served, view_changes, lost = drive(ops), 0, 0
    for w in range(waves):
        joiners = np.arange(n + w * wave, n + (w + 1) * wave)
        sim.request_joins(joiners)
        view_changes += settle(n + wave)
        lost += lost_acked()
        total_served += drive(ops)
        sim.leave(joiners)
        view_changes += settle(n)
        lost += lost_acked()
        total_served += drive(ops)
    wall = time.perf_counter() - t0
    return {
        "config": (
            f"serving sawtooth: {n} nodes ± {wave} x {waves} waves, "
            f"closed-loop Get/Put riding every view change (seed {seed})"
        ),
        "n": n,
        "virtual_ms": sim.virtual_ms,
        "wall_s": round(wall, 3),
        "cut_ok": bool(sim.membership_size == n and lost == 0),
        "view_changes": view_changes,
        "ops_served": total_served,
        "lost_acked_writes": lost,
    }


def scenario_rolling_restart(seed=37, n=4, ops_per_wave=12):
    """Rolling restart under serving load (PR 16's durability oracle): every
    node, in sequence, crashes abruptly (WAL torn mid-flight, no clean
    shutdown) and rejoins with the SAME durability directory before the
    failure detector concludes -- the persisted NodeId drives the
    HOSTNAME_ALREADY_IN_RING rejoin fast path, recovery replays
    log-over-snapshot, and the verified handoff pull catches the replica
    up. The oracle: every node keeps its original identity across its
    restart, ZERO acked writes are lost over the whole wave, and no rejoin
    leaves anyone else evicted (a restart is not a membership event)."""
    import os
    import shutil
    import tempfile

    from rapid_tpu.settings import DurabilitySettings, Settings
    sys.path.insert(0, "tests")
    from harness import ClusterHarness

    t0 = time.perf_counter()
    root = tempfile.mkdtemp(prefix="rapid-rolling-restart-")
    settings = Settings(
        durability=DurabilitySettings(enabled=True, fsync_policy=0)
    )
    h = ClusterHarness(seed=seed, settings=settings)
    placement = {"partitions": 16, "replicas": 3, "seed": 7}
    dirs = {i: os.path.join(root, f"node{i}") for i in range(n)}
    try:
        h.start_seed(0, placement=placement, serving=True,
                     durability=dirs[0])
        for i in range(1, n):
            h.join(i, placement=placement, serving=True, durability=dirs[i])
        h.wait_and_verify_agreement(n)
        identities = {
            i: h.instances[h.addr(i)].get_partition_store().node_id
            for i in range(n)
        }
        all_addrs = {h.addr(i) for i in range(n)}
        acked: dict = {}
        write_seq = 0

        def drive(client, count: int) -> None:
            nonlocal write_seq
            for _ in range(count):
                key = b"roll-%02d" % (write_seq % 24)
                value = b"w-%d" % write_seq
                write_seq += 1
                p = client.serving_put(key, value)
                ok = h.scheduler.run_until(p.done, timeout_ms=60_000)
                if ok and p.peek().status == 0:
                    acked[key] = value

        identity_ok = True
        replayed_total = 0
        spurious = 0
        drive(h.instances[h.addr(0)], ops_per_wave)
        for i in range(n):
            survivor = h.addr((i + 1) % n)
            victim = h.instances[h.addr(i)]
            victim.get_partition_store().crash()  # power loss, not clean stop
            h.fail_nodes([h.addr(i)])
            h.blacklist.discard(h.addr(i))  # back before the FD concludes
            revived = h.join(i, seed_index=(i + 1) % n, placement=placement,
                             serving=True, durability=dirs[i])
            h.wait_and_verify_agreement(n)
            store = revived.get_partition_store()
            identity_ok &= store.node_id == identities[i]
            replayed_total += store.durability_stats()["replayed_records"]
            if set(h.instances[survivor].get_memberlist()) != all_addrs:
                spurious += 1
            drive(h.instances[survivor], ops_per_wave)
        # every acked write must read back through a survivor (newer
        # versions are fine -- later writes win; NOT_FOUND is a loss)
        lost = 0
        reader = h.instances[h.addr(0)]
        for key in sorted(acked):
            p = reader.serving_get(key)
            h.scheduler.run_until(p.done, timeout_ms=60_000)
            ack = p.peek()
            if ack.status != 0 or ack.version == 0:
                lost += 1
        virtual_ms = h.scheduler.now_ms()
        h.shutdown()
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return {
        "config": (
            f"rolling restart: {n} nodes each crash + rejoin with their "
            f"WAL dir under serving load (seed {seed})"
        ),
        "n": n,
        "virtual_ms": virtual_ms,
        "wall_s": round(time.perf_counter() - t0, 3),
        "cut_ok": bool(identity_ok and lost == 0 and spurious == 0),
        "identities_retained": bool(identity_ok),
        "lost_acked_writes": lost,
        "spurious_view_changes": spurious,
        "replayed_records": int(replayed_total),
    }


def scenario_overload_recover(seed=43, n=16, rate_base=400.0,
                              rate_burst=2500.0):
    """Overload-then-recover through a leader eviction (the SLO plane's
    end-to-end demonstration): an open-loop arrival stream runs at a
    sustainable baseline rate, then bursts past serving capacity while the
    busiest partition leader crashes -- queueing delay (measured from
    *scheduled* arrival, so nothing is coordinated-omitted) burns the
    latency SLO and the fast-pair burn alert fires mid-churn. The decided
    view plus the rate dropping back to baseline must (a) let the
    fast-window alerts clear, (b) leave every fired alert attributed to
    the view-change episode's trace id, and (c) pass the
    metastable-recovery checker on the scenario's own client history."""
    from rapid_tpu.search.checkers import (
        ClientOp,
        InvariantViolation,
        check_metastable_recovery,
    )
    from rapid_tpu.settings import SLOSettings
    from rapid_tpu.sim.driver import Simulator
    from rapid_tpu.slo import OpenLoopGenerator, describe

    t0 = time.perf_counter()
    sim = Simulator(n, seed=seed)
    sim.enable_placement(partitions=64, replicas=3)
    sim.enable_handoff(chunk_ms=1)
    sim.enable_serving()
    # burn windows compressed onto virtual time: fast pair 5m/1h ->
    # 300ms/3.6s, so the whole fire->attribute->clear cycle fits one run
    plane = sim.enable_slo(SLOSettings(enabled=True, window_scale=0.001))
    keys = [b"ovr-%03d" % i for i in range(32)]
    for i, key in enumerate(keys):
        ack = sim.serving_put(key, b"seed-%d" % i)
        assert ack.status == ack.STATUS_OK
    history: "list[ClientOp]" = []

    def drive(gen: OpenLoopGenerator, n_ops: int) -> None:
        gen.rebase(sim.virtual_ms)
        for a, status, lat in sim.serving_drive_open_loop(
            gen.arrivals(n_ops)
        ):
            history.append(ClientOp(
                client=f"c{a.client}", op=a.op, key=a.key, value=a.value,
                version=0, status=int(status),
                invoke_ms=int(a.at_ms), complete_ms=int(a.at_ms + lat),
            ))

    base = OpenLoopGenerator(
        rate_base, keys, put_fraction=0.2, seed=seed,
    )
    drive(base, 480)  # ~1.2s virtual of healthy baseline
    false_alerts = plane.firing_count()

    # overload + leader crash: the busiest leader slot goes down while the
    # arrival rate jumps past capacity -- redirects and quorum reads slow
    # service exactly when the queue is growing fastest
    faulted_from = sim.virtual_ms
    leaders = sim.placement.assign[:, 0].astype(int)
    victim = int(np.argmax(np.bincount(leaders[leaders > 0])))
    sim.crash(np.array([victim]))
    burst = OpenLoopGenerator(
        rate_burst, keys, put_fraction=0.2, seed=seed + 1,
    )
    drive(burst, 1200)
    fired_during_churn = plane.firing_count()
    rec = sim.run_until_decision(max_rounds=64, batch=16)
    assert rec is not None, "overload-recover: no view decision"
    assert set(int(c) for c in rec.cut) == {victim}, (
        "overload-recover: cut parity"
    )

    # recovery: baseline rate until the fast pair's long window (3.6s
    # scaled) has fully drained the churn's error mass
    healed_at = sim.virtual_ms
    drive(base, 1700)
    plane.tick(sim.virtual_ms, force=True)
    plane.attribute(sim.recorder.tail(4096))

    installs = [
        e for e in sim.recorder.tail(4096)
        if e["kind"] == "view_install" and e["detail"].get("trace_id")
    ]
    expected_trace = int(installs[-1]["detail"]["trace_id"]) if installs else 0
    fired = [a for a in plane.alerts() if a.fired_count > 0]
    attributed_ok = bool(fired) and all(
        a.attributed is not None
        and a.attributed.kind == "view-change"
        and int(a.attributed.trace_id) == expected_trace
        for a in fired
    )
    fast_cleared = all(
        not a.firing for a in plane.alerts() if a.window == "fast"
    )
    try:
        check_metastable_recovery(
            history, faulted_from_ms=faulted_from, healed_at_ms=healed_at,
        )
        recovered = True
    except InvariantViolation:
        recovered = False

    wall = time.perf_counter() - t0
    return {
        "config": (
            f"overload-recover: {n} nodes, open-loop "
            f"{rate_base:.0f}->{rate_burst:.0f}/s burst through a leader "
            f"crash (seed {seed})"
        ),
        "n": n,
        "virtual_ms": sim.virtual_ms,
        "wall_s": round(wall, 3),
        "cut_ok": bool(
            false_alerts == 0 and fired_during_churn > 0
            and fast_cleared and attributed_ok and recovered
        ),
        "alerts_fired_during_churn": fired_during_churn,
        "fast_alerts_cleared": fast_cleared,
        "attributed": [
            {"alert": a.name, "episode": describe(a.attributed)}
            for a in fired
        ],
        "metastable_recovery_ok": recovered,
    }


def scenario_pinned_plan(path, seed=None):
    """Replay one pinned nemesis-search corpus file (a probe spec JSON
    written by ``tools/hunt.py --pin``): build the FaultPlan back through
    the validating builders, run it on its recorded harness, and demand
    ZERO invariant violations -- each corpus file is the shrunk witness of
    a bug the search once found, kept as a regression tripwire. ``seed``
    overrides the plan seed (same fault shape, different interleaving)."""
    from rapid_tpu.search.runner import run_probe

    with open(path) as fh:
        spec = json.load(fh)
    probe = {
        k: v for k, v in spec.items()
        if k not in ("name", "description", "expect")
    }
    if seed is not None:
        probe["plan"] = {**probe["plan"], "seed": seed}
    t0 = time.perf_counter()
    result = run_probe(probe)
    return {
        "config": (
            f"pinned plan {spec.get('name', path)}: "
            f"{len(probe['plan'].get('rules', []))} rule(s) on the "
            f"{probe.get('harness', 'engine')} harness"
        ),
        "n": probe.get("n", 5),
        "virtual_ms": result.info.get("virtual_ms"),
        "wall_s": round(time.perf_counter() - t0, 3),
        "cut_ok": not result.violated,
        "violations": [v["invariant"] for v in result.violations],
        "coverage_signals": len(result.coverage),
    }


# ---------------------------------------------------------------------------
# the registry table and batteries
# ---------------------------------------------------------------------------

register("cross-plane-10", scenario_10_node_cross_plane, seed=1)
register("crash-1k", scenario_crash, n=1000, n_fail=1, seed=100,
         label="1k virtual nodes, single crash-stop fault")
register("crash-10k", scenario_crash, n=10_000, n_fail=100, seed=200,
         label="10k virtual nodes, 1% correlated crash burst")
register("one-way-loss-50k", scenario_one_way_loss, n=50_000, n_fail=500,
         seed=300)
register("flip-flop-join-100k", scenario_flip_flop_with_join_wave,
         n=100_000, capacity=100_100, seed=400)
register("nemesis-protocol", scenario_nemesis_protocol, seed=7, n=5)
register("nemesis-smoke", scenario_nemesis_smoke, n=1000, seed=7)
register("wan-zone-loss", scenario_wan_zone_loss, seed=11)
register("hierarchy-zone-churn", scenario_hierarchy_zone_churn, seed=19)
register("gray-slow-node", scenario_gray_slow_node, seed=7)
register("gray-flapping", scenario_gray_flapping, seed=17)
register("clock-skew", scenario_clock_skew, seed=13)
register("rolling-upgrade", scenario_rolling_upgrade, seed=21)
register("serving-sawtooth", scenario_serving_sawtooth, seed=31)
register("rolling-restart", scenario_rolling_restart, seed=37)
register("overload-recover", scenario_overload_recover, seed=43)
# 10x the north-star scale (VERDICT r4 item 3): every failure class the
# paper holds stable, at 1M, with cut parity AND the from-scratch
# configuration-id cross-check
register("crash-1m", scenario_crash, n=1_000_000, n_fail=10_000, seed=500,
         label="1M virtual nodes, 1% correlated crash burst (10x north star)")
register("one-way-loss-1m", scenario_one_way_loss, n=1_000_000,
         n_fail=10_000, seed=501)
register("flip-flop-join-1m", scenario_flip_flop_with_join_wave,
         n=1_000_000, capacity=1_001_000, seed=502)

BATTERY = [
    "cross-plane-10", "crash-1k", "crash-10k", "one-way-loss-50k",
    "flip-flop-join-100k", "nemesis-smoke", "wan-zone-loss",
    "hierarchy-zone-churn",
    "gray-slow-node", "gray-flapping", "clock-skew", "rolling-upgrade",
    "serving-sawtooth", "rolling-restart", "overload-recover",
]
SCALE_1M = ["crash-1m", "one-way-loss-1m", "flip-flop-join-1m"]

# every pinned corpus plan (tools/hunt.py --pin scenarios/corpus) joins the
# registry AND the battery as a regression scenario: the shrunk witness of
# a violation the nemesis search once found must stay green forever
_CORPUS_DIR = pathlib.Path(__file__).parent / "scenarios" / "corpus"
for _pin in sorted(_CORPUS_DIR.glob("*.json")):
    _name = f"corpus-{_pin.stem}"
    register(_name, scenario_pinned_plan, path=str(_pin))
    BATTERY.append(_name)


def _flag_value(flag: str) -> str:
    """Value of ``--flag PATH`` in sys.argv, or '' when absent."""
    if flag not in sys.argv:
        return ""
    at = sys.argv.index(flag)
    return sys.argv[at + 1] if len(sys.argv) > at + 1 else ""


def _write_telemetry() -> None:
    """Honor --trace-out / --metrics-out: export the process-global
    telemetry plane (every scenario's protocol nodes + simulators merged).
    The Chrome trace loads in Perfetto / chrome://tracing; the metrics file
    is Prometheus text exposition (see ARCHITECTURE.md, Telemetry plane)."""
    from rapid_tpu.observability import write_chrome_trace, write_prometheus

    trace_out = _flag_value("--trace-out")
    metrics_out = _flag_value("--metrics-out")
    if trace_out:
        write_chrome_trace(trace_out)
        print(json.dumps({"trace_out": trace_out}))
    if metrics_out:
        write_prometheus(metrics_out)
        print(json.dumps({"metrics_out": metrics_out}))


def main() -> None:
    if "--list" in sys.argv:
        # pure registry dump: no jax import, usable on any host
        for name, (fn, params) in REGISTRY.items():
            battery = (
                "battery" if name in BATTERY
                else "scale-1m" if name in SCALE_1M else "on-demand"
            )
            print(json.dumps(
                {"scenario": name, "fn": fn.__name__, "set": battery,
                 **params}
            ))
        return
    if "--tpu" not in sys.argv:
        # pin the CPU backend via the CONFIG value (an injected accelerator
        # plugin ignores the env var, and a dead remote-TPU tunnel hangs
        # device init); pass --tpu to run on real hardware
        import jax

        jax.config.update("jax_platforms", "cpu")
    if "--fault-plan" in sys.argv:
        # replay one seeded nemesis FaultPlan on the protocol plane AND the
        # device plane, then exit (with telemetry exports if requested):
        #   python scenarios.py --fault-plan [seed] \
        #       [--trace-out trace.json] [--metrics-out metrics.prom]
        arg = _flag_value("--fault-plan")
        seed = int(arg) if arg.lstrip("-").isdigit() else 7
        print(json.dumps(run_scenario("nemesis-protocol", seed=seed)))
        print(json.dumps(run_scenario("nemesis-smoke", seed=seed)))
        _write_telemetry()
        return
    plan_file = _flag_value("--plan")
    if plan_file:
        # replay one probe-spec JSON (pinned corpus file or hand-written):
        #   python scenarios.py --plan scenarios/corpus/foo.json [--seed 9]
        seed_arg = _flag_value("--seed")
        seed = int(seed_arg) if seed_arg else None
        print(json.dumps(scenario_pinned_plan(plan_file, seed=seed)))
        _write_telemetry()
        return
    chosen = _flag_value("--scenario")
    if chosen:
        if chosen not in REGISTRY:
            known = ", ".join(REGISTRY)
            raise SystemExit(f"unknown scenario {chosen!r}; known: {known}")
        seed_arg = _flag_value("--seed")
        seed = int(seed_arg) if seed_arg else None
        print(json.dumps(run_scenario(chosen, seed=seed)))
        _write_telemetry()
        return
    names = BATTERY + (SCALE_1M if "--scale-1m" in sys.argv else [])
    # --cells N arms the hierarchy mirror on the 1M-scale sims: same
    # seeds, same faults, plus the composed-view maintenance and its
    # parent-round bill in each result's "hierarchy" digest
    cells_arg = _flag_value("--cells")
    cells = int(cells_arg) if cells_arg else 0
    for name in names:
        overrides = {"cells": cells} if cells and name in SCALE_1M else {}
        print(json.dumps(run_scenario(name, **overrides)))
    _write_telemetry()


if __name__ == "__main__":
    main()
